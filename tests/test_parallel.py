"""Parallel strategies on the 8-device virtual CPU mesh.

Core invariants:
- dense (DWBP-tap) DP training on N devices == single-device training on the
  concatenated batch with summed gradients (exact parity).
- SFB produces bit-equal gradients to dense for FC layers.
- top-k compressed sync keeps replicas consistent.
- SSP staleness s: replicas may diverge between syncs, reconcile every s+1.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.core.net import Net
from poseidon_tpu.models import zoo
from poseidon_tpu.parallel import (
    CommConfig, SFB, auto_strategies, build_eval_step, build_ssp_train_step,
    build_train_step, init_ssp_state, init_train_state, make_mesh)
from poseidon_tpu.proto.messages import SolverParameter
from poseidon_tpu.solvers.updates import init_state, make_update_fn
from poseidon_tpu.parallel.trainer import param_mults

N_DEV = 8
BATCH = 16  # global batch; 2 per device


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() == N_DEV, "conftest must provide 8 cpu devices"
    return make_mesh()


@pytest.fixture(scope="module")
def lenet_net():
    return Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
               source_shapes=zoo.lenet_shapes(BATCH // N_DEV))


def _global_batch(rng):
    return {
        "data": jnp.asarray(rng.randn(BATCH, 1, 28, 28).astype(np.float32)),
        "label": jnp.asarray(rng.randint(0, 10, size=(BATCH,))),
    }


def _single_device_reference(net, sp, params, batch, n_steps, rng_np):
    """Sum of per-shard mean-gradients == what dense DP computes."""
    update = make_update_fn(sp, param_mults(net))
    state = init_state(params)
    shard = BATCH // N_DEV

    for step in range(n_steps):
        def loss_fn(p):
            total = 0.0
            for d in range(N_DEV):
                sl = {k: v[d * shard:(d + 1) * shard] for k, v in batch.items()}
                total = total + net.apply(p, sl, train=True,
                                          rng=jax.random.PRNGKey(99)).loss
            return total
        grads = jax.grad(loss_fn)(params)
        params, state = update(params, grads, state)
    return params


def test_dense_dp_matches_single_device(mesh, lenet_net, rng_np):
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)

    # Use a fixed rng fed identically; dropout-free net so rng is inert.
    ts = build_train_step(lenet_net, sp, mesh, CommConfig(reduce="sum"),
                          donate=False)
    # ONE step: the re-layout contract at its strongest — sharded and
    # single-device params agree to f32 epsilon (measured 3e-8).
    p1, s1, _ = ts.step(params, init_train_state(params), batch,
                        jax.random.PRNGKey(99))
    want1 = _single_device_reference(lenet_net, sp, params, batch, 1, rng_np)
    for l in want1:
        for k in want1[l]:
            np.testing.assert_allclose(
                np.asarray(p1[l][k]), np.asarray(want1[l][k]),
                rtol=1e-5, atol=1e-6, err_msg=f"step1 {l}/{k}")

    p, s = params, init_train_state(params)
    for _ in range(3):
        p, s, metrics = ts.step(p, s, batch, jax.random.PRNGKey(99))
    want = _single_device_reference(lenet_net, sp, params, batch, 3, rng_np)
    for l in want:
        for k in want[l]:
            # Over multiple steps exactness is unattainable for ANY two
            # valid schedules: psum tree-reduction order differs from the
            # sequential host sum by ~1 ulp, and max-pool's argmax can flip
            # on a near-tie once params differ by epsilon, re-routing one
            # window's gradient entirely (observed: 1/500 conv1 weights at
            # 8e-4 after 3 momentum steps; step 1 is at 3e-8).
            np.testing.assert_allclose(
                np.asarray(p[l][k]), np.asarray(want[l][k]),
                rtol=2e-2, atol=1.5e-3, err_msg=f"{l}/{k}")


def test_sfb_matches_dense(mesh, lenet_net, rng_np):
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)

    dense = build_train_step(lenet_net, sp, mesh, CommConfig(), donate=False)
    sfb = build_train_step(
        lenet_net, sp, mesh,
        CommConfig(layer_strategies={"ip1": SFB, "ip2": SFB}), donate=False)

    mk = init_train_state
    p1, s1, m1 = dense.step(params, mk(params), batch, jax.random.PRNGKey(7))
    p2, s2, m2 = sfb.step(params, mk(params), batch, jax.random.PRNGKey(7))
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-6)
    for l in p1:
        for k in p1[l]:
            np.testing.assert_allclose(
                np.asarray(p1[l][k]), np.asarray(p2[l][k]),
                rtol=1e-4, atol=1e-7, err_msg=f"{l}/{k}")


def test_dense_fused_matches_dense(mesh, lenet_net, rng_np):
    """The no-overlap A/B baseline (one bulk psum after backward) must be
    numerically identical to the in-backward DWBP taps — same psums, just
    scheduled at the end."""
    from poseidon_tpu.parallel import DENSE_FUSED
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    dense = build_train_step(lenet_net, sp, mesh, CommConfig(), donate=False)
    fused = build_train_step(
        lenet_net, sp, mesh,
        CommConfig(default_strategy=DENSE_FUSED), donate=False)
    p1, _, m1 = dense.step(params, init_train_state(params), batch,
                           jax.random.PRNGKey(7))
    p2, _, m2 = fused.step(params, init_train_state(params), batch,
                           jax.random.PRNGKey(7))
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-6)
    for l in p1:
        for k in p1[l]:
            np.testing.assert_allclose(
                np.asarray(p1[l][k]), np.asarray(p2[l][k]),
                rtol=1e-5, atol=1e-7, err_msg=f"{l}/{k}")


def test_adarevision_matches_server_formula(mesh, lenet_net, rng_np):
    """server_logic='adarevision' must reproduce the reference server's
    update rule exactly (adarevision_server_table_logic.cpp:52-175): for
    each group's accumulated gradient u applied in group order,
    z += u*(u + 2*g_bck); zmax = max(zmax, z); delta = -eta*u +
    (eta_old - eta)*g_bck with eta = eta0/sqrt(zmax); g_bck accumulates
    the within-boundary updates (snapshots are boundary-aligned here, so
    g_bck starts at 0 each sync). Verified against a NumPy replica fed the
    per-shard gradients."""
    eta0 = 0.05
    comm = CommConfig(server_logic="adarevision", adarev_init_step=eta0)
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.0,
                         weight_decay=0.0)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    ts = build_ssp_train_step(lenet_net, sp, mesh, staleness=0, comm=comm)

    # per-shard raw gradients + numpy copies BEFORE the step: the jitted
    # step donates its state, whose anchor aliases `params`
    shard = BATCH // N_DEV
    u = []
    for d in range(N_DEV):
        sl = {k: v[d * shard:(d + 1) * shard] for k, v in batch.items()}
        u.append(jax.device_get(jax.grad(
            lambda p: lenet_net.apply(p, sl, train=True,
                                      rng=jax.random.PRNGKey(9)).loss)(params)))
    params0 = jax.device_get(params)

    state = init_ssp_state(params, N_DEV, comm)
    state, m = ts.step(state, batch, jax.random.PRNGKey(9))
    for l in params0:
        for k in params0[l]:
            av = np.asarray(params0[l][k], np.float64)
            z = np.ones_like(av)
            zmax = np.ones_like(av)
            g_bck = np.zeros_like(av)
            for d in range(N_DEV):
                ug = np.asarray(u[d][l][k], np.float64)
                eta_old = eta0 / np.sqrt(zmax)
                z = z + ug * (ug + 2.0 * g_bck)
                zmax = np.maximum(zmax, z)
                eta = eta0 / np.sqrt(zmax)
                av = av - eta * ug + (eta_old - eta) * g_bck
                g_bck = g_bck + ug
            np.testing.assert_allclose(
                np.asarray(state.anchor_params[l][k]), av,
                rtol=2e-4, atol=1e-6, err_msg=f"{l}/{k}")
            # locals refreshed from the server at the boundary
            np.testing.assert_array_equal(
                np.asarray(state.local_params[l][k][0]),
                np.asarray(state.anchor_params[l][k]))


def test_adarevision_converges_under_staleness(mesh, lenet_net, rng_np):
    """adarevision + staleness: the delay-corrected server keeps replicas
    consistent at boundaries and the loss goes down."""
    # eta0 scales the SUM of group updates (the server applies every
    # client's u in full — the same sum semantics that made PMLS retune lr
    # per cluster size); ~base_lr/n_groups is the stable regime
    comm = CommConfig(server_logic="adarevision", adarev_init_step=0.005)
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    ts = build_ssp_train_step(lenet_net, sp, mesh, staleness=1, comm=comm)
    state = init_ssp_state(params, N_DEV, comm)
    batch = _global_batch(rng_np)  # fixed batch: a learnable objective
    losses = []
    for i in range(40):
        state, m = ts.step(state, batch, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    # the trajectory saw-tooths (local preview vs anchor reset); judge the
    # envelope, not adjacent steps
    assert min(losses[-6:]) < 0.1, losses
    # oplog drains at every boundary (staleness 1 -> sync on even its)
    for lname, lp in state.adarev_gsum.items():
        for pname, v in lp.items():
            assert np.isfinite(np.asarray(v)).all()
    z = state.adarev_server["ip2"]["w"]["zmax"]
    assert float(jnp.min(z)) >= 1.0  # AdaRevisionRow init, monotone max


def test_adarevision_rejects_topk():
    from poseidon_tpu.parallel import TOPK
    net = Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
              source_shapes=zoo.lenet_shapes(2))
    comm = CommConfig(server_logic="adarevision",
                      layer_strategies={"ip1": TOPK})
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed")
    with pytest.raises(ValueError, match="adarevision"):
        build_ssp_train_step(net, sp, make_mesh(), staleness=1, comm=comm)


def test_iter_size_matches_big_batch(mesh, rng_np):
    """Gradient accumulation (SolverParameter.iter_size, Caffe's V2
    surface): batch_size B at iter_size K must equal batch_size B*K — same
    samples, same mean gradient, same momentum trajectory. Sample-to-device
    assignment differs between the two layouts, but under reduce='mean'
    every sample contributes 1/(B*K) either way."""
    K = 4
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    small = Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
                source_shapes=zoo.lenet_shapes(BATCH // N_DEV))
    big = Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
              source_shapes=zoo.lenet_shapes(BATCH * K // N_DEV))
    params = small.init(jax.random.PRNGKey(0))
    data = rng_np.randn(BATCH * K, 1, 28, 28).astype(np.float32)
    labels = rng_np.randint(0, 10, size=(BATCH * K,)).astype(np.int32)

    ts_acc = build_train_step(small, sp, mesh, CommConfig(), donate=False,
                              iter_size=K)
    assert ts_acc.iter_size == K
    ts_big = build_train_step(big, sp, mesh, CommConfig(), donate=False)
    b_acc = {"data": jnp.asarray(data.reshape(K, BATCH, 1, 28, 28)),
             "label": jnp.asarray(labels.reshape(K, BATCH))}
    b_big = {"data": jnp.asarray(data), "label": jnp.asarray(labels)}

    pa, sa = params, init_train_state(params)
    pb, sb = params, init_train_state(params)
    for _ in range(2):  # two steps: momentum history must match too
        pa, sa, ma = ts_acc.step(pa, sa, b_acc, jax.random.PRNGKey(7))
        pb, sb, mb = ts_big.step(pb, sb, b_big, jax.random.PRNGKey(7))
    assert float(ma["loss"]) == pytest.approx(float(mb["loss"]), rel=1e-5)
    for l in pa:
        for k in pa[l]:
            np.testing.assert_allclose(
                np.asarray(pa[l][k]), np.asarray(pb[l][k]),
                rtol=1e-4, atol=1e-6, err_msg=f"{l}/{k}")


def test_iter_size_composes_with_topk(mesh, lenet_net, rng_np):
    """TOPK compression applies to the ACCUMULATED gradient under
    iter_size; replicas stay consistent and the error residual carries."""
    from poseidon_tpu.parallel import TOPK
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    comm = CommConfig(layer_strategies={"ip1": TOPK}, topk_fraction=0.05)
    params = lenet_net.init(jax.random.PRNGKey(0))
    ts = build_train_step(lenet_net, sp, mesh, comm, donate=False,
                          iter_size=2)
    batch = {"data": jnp.asarray(rng_np.randn(2, BATCH, 1, 28, 28)
                                 .astype(np.float32)),
             "label": jnp.asarray(rng_np.randint(0, 10, size=(2, BATCH))
                                  .astype(np.int32))}
    p, s = params, init_train_state(params, comm, N_DEV)
    for _ in range(3):
        p, s, m = ts.step(p, s, batch, jax.random.PRNGKey(7))
    assert np.isfinite(float(m["loss"]))
    # residual is nonzero (something was withheld) and params are finite
    resid = s.comm_error["ip1"]["w"]
    assert float(jnp.abs(resid).sum()) > 0


def test_dwbp_bucketed_matches_dense(mesh, lenet_net, rng_np):
    """Chained (bucketed) DWBP taps are an ORDERING change only: the psums
    are gated on chain tokens, never rescaled — parameters after a step must
    match plain dense bit-for-bit (the gate is the identity for any finite
    token), and the compiled program must keep the buckets' collectives
    DISTINCT (the whole point: round 3 showed the combiner merges unchained
    taps into one all-reduce, evidence/dwbp_schedule.json)."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    dense = build_train_step(lenet_net, sp, mesh, CommConfig(), donate=False)
    # bucket 0 MB = one chain stage per parameter (per-blob granularity)
    chained = build_train_step(lenet_net, sp, mesh,
                               CommConfig(dwbp_bucket_mb=0), donate=False)
    p1, _, m1 = dense.step(params, init_train_state(params), batch,
                           jax.random.PRNGKey(7))
    p2, _, m2 = chained.step(params, init_train_state(params), batch,
                             jax.random.PRNGKey(7))
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-6)
    for l in p1:
        for k in p1[l]:
            np.testing.assert_array_equal(
                np.asarray(p1[l][k]), np.asarray(p2[l][k]),
                err_msg=f"{l}/{k}")

    # distinctness: the chained program must carry MORE gradient all-reduces
    # than the unchained one (whose taps the combiner merges into ~1)
    def n_all_reduce(ts):
        hlo = ts.lowerable.lower(params, init_train_state(params), batch,
                                 jax.random.PRNGKey(7)).compile().as_text()
        return sum(line.count(" all-reduce(") + line.count(" all-reduce-start(")
                   for line in hlo.splitlines())

    n_dense, n_chained = n_all_reduce(dense), n_all_reduce(chained)
    # lenet has 4 param layers x (w, b) = 8 taps; metrics psums add a couple
    assert n_chained > n_dense, (n_dense, n_chained)
    assert n_chained >= 8


def test_dwbp_bucket_grouping(mesh, lenet_net, rng_np):
    """A large bucket budget must group taps: strictly fewer collectives
    than per-blob chaining, while still matching dense numerically."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)

    def n_all_reduce(cfg):
        ts = build_train_step(lenet_net, sp, mesh, cfg, donate=False)
        hlo = ts.lowerable.lower(params, init_train_state(params), batch,
                                 jax.random.PRNGKey(7)).compile().as_text()
        return sum(line.count(" all-reduce(") + line.count(" all-reduce-start(")
                   for line in hlo.splitlines())

    per_blob = n_all_reduce(CommConfig(dwbp_bucket_mb=0))
    bucketed = n_all_reduce(CommConfig(dwbp_bucket_mb=1.0))
    assert bucketed < per_blob, (bucketed, per_blob)


def test_arena_sfb_topk_layers_opt_out(mesh, lenet_net, rng_np):
    """SFB and TOPK layers keep their custom comm paths under the flat
    parameter arena: the arena layout excludes them, and a mixed-strategy
    step is bit-identical with the arena on and off (same SFB factor
    gathers, same TOPK compression + error feedback, same DENSE arena
    leaves)."""
    import dataclasses
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    comm = CommConfig(layer_strategies={"ip1": SFB, "conv2": "topk"},
                      topk_fraction=0.1)
    results = []
    for arena_on in (True, False):
        cc = dataclasses.replace(comm, param_arena=arena_on)
        ts = build_train_step(lenet_net, sp, mesh, cc, donate=False)
        if arena_on:
            # opt-outs: only the DENSE layers live in the arena
            assert ts.arena is not None
            assert ts.arena.layers == {"conv1", "ip2"}
        p, s = params, init_train_state(params, cc, N_DEV)
        for i in range(2):
            p, s, m = ts.step(p, s, batch, jax.random.PRNGKey(i))
        results.append((p, s))
    (p1, s1), (p2, s2) = results
    for l in p1:
        for k in p1[l]:
            np.testing.assert_array_equal(
                np.asarray(p1[l][k]), np.asarray(p2[l][k]),
                err_msg=f"{l}/{k}")
    # TOPK error-feedback residuals agree too (same compression inputs)
    for l in s1.comm_error:
        for k in s1.comm_error[l]:
            np.testing.assert_array_equal(
                np.asarray(s1.comm_error[l][k]),
                np.asarray(s2.comm_error[l][k]), err_msg=f"err {l}/{k}")


def test_auto_strategies_picks_sfb_for_big_fc():
    net = Net(zoo.alexnet(), phase="TRAIN",
              source_shapes=zoo.alexnet_shapes(32))
    strats = auto_strategies(net)
    # fc6: 4096x9216 weight vs batch 32: SFB clearly wins
    assert strats.get("fc6") == SFB
    assert strats.get("fc7") == SFB


def test_topk_sync_keeps_replicas_consistent(mesh, lenet_net, rng_np):
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed")
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    cc = CommConfig(default_strategy="topk", topk_fraction=0.1)
    ts = build_train_step(lenet_net, sp, mesh, cc, donate=False)
    p, s = params, init_train_state(params, cc, N_DEV)
    for _ in range(2):
        p, s, m = ts.step(p, s, batch, jax.random.PRNGKey(3))
    # params replicated => no NaNs, finite, and training moved
    w = np.asarray(p["conv1"]["w"])
    assert np.isfinite(w).all()
    assert np.abs(w - np.asarray(params["conv1"]["w"])).max() > 0


def test_topk_error_feedback_preserves_convergence(mesh, lenet_net, rng_np):
    """TOPK@10% must land within a modest margin of dense training after N
    steps — the error-feedback guarantee (delayed, not lost). Also exercises
    comm_error across snapshot/restore mid-run."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    n_iters = 14

    dense = build_train_step(lenet_net, sp, mesh, CommConfig(), donate=False)
    p, s = params, init_train_state(params)
    for i in range(n_iters):
        p, s, m_dense = dense.step(p, s, batch, jax.random.PRNGKey(i))

    cc = CommConfig(default_strategy="topk", topk_fraction=0.1)
    ts = build_train_step(lenet_net, sp, mesh, cc, donate=False)
    p, s = params, init_train_state(params, cc, N_DEV)
    for i in range(n_iters // 2):
        p, s, m = ts.step(p, s, batch, jax.random.PRNGKey(i))

    # mid-run snapshot/restore roundtrip must preserve the residuals exactly
    from poseidon_tpu.runtime.checkpoint import restore, snapshot
    import tempfile, os
    with tempfile.TemporaryDirectory() as d:
        _, state_path = snapshot(os.path.join(d, "tk"), lenet_net, p, s)
        p2, s2 = restore(state_path)
        for l, lp_ in s.comm_error.items():
            for k in lp_:
                np.testing.assert_array_equal(
                    np.asarray(s2.comm_error[l][k]), np.asarray(lp_[k]))
    for i in range(n_iters // 2, n_iters):
        p2, s2, m_topk = ts.step(p2, s2, batch, jax.random.PRNGKey(i))

    start = float(np.log(10))
    d_loss, t_loss = float(m_dense["loss"]), float(m_topk["loss"])
    assert d_loss < 0.5 * start
    # within half of dense's progress despite sending only 10% of entries
    assert t_loss < d_loss + 0.5 * (start - d_loss), \
        f"topk {t_loss} vs dense {d_loss}"


def test_eval_step(mesh, rng_np):
    net = Net(zoo.lenet(with_accuracy=True), phase="TEST",
              source_shapes=zoo.lenet_shapes(BATCH // N_DEV))
    params = net.init(jax.random.PRNGKey(0))
    ev = build_eval_step(net, mesh)
    metrics = ev(params, _global_batch(rng_np))
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0
    assert float(metrics["loss"]) == pytest.approx(np.log(10), rel=0.3)


def test_ssp_bounded_staleness(mesh, lenet_net, rng_np):
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    staleness = 2
    ts = build_ssp_train_step(lenet_net, sp, mesh, staleness)
    st = init_ssp_state(params, N_DEV)
    for i in range(1, 7):
        st, m = ts.step(st, batch, jax.random.PRNGKey(i))
        local = np.asarray(st.local_params["conv1"]["w"])
        spread = np.abs(local - local[0:1]).max()
        if i % (staleness + 1) == 0:
            # just synced: all replicas identical
            assert spread == 0.0, f"iter {i}"
        else:
            # replicas allowed to drift between syncs
            assert np.isfinite(local).all()
    assert np.isfinite(float(m["loss"]))


def test_ssp_converges_close_to_sync(mesh, lenet_net, rng_np):
    """SSP s=2 must track synchronous training: after N iters on a fixed
    batch, its loss lands within a small margin of the s=0 loss (the bounded
    -staleness convergence claim, ssp_consistency_controller.cpp)."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    n_iters = 9  # multiple of period so the final iter is a sync point

    sync_ts = build_train_step(lenet_net, sp, mesh, CommConfig(),
                               donate=False)
    p, s = params, init_train_state(params)
    for i in range(n_iters):
        p, s, m_sync = sync_ts.step(p, s, batch, jax.random.PRNGKey(i))

    ssp_ts = build_ssp_train_step(lenet_net, sp, mesh, staleness=2)
    st = init_ssp_state(params, N_DEV)
    for i in range(n_iters):
        st, m_ssp = ssp_ts.step(st, batch, jax.random.PRNGKey(i))

    sync_loss, ssp_loss = float(m_sync["loss"]), float(m_ssp["loss"])
    start_loss = float(np.log(10))
    # both should have made real progress, and SSP shouldn't lag sync by more
    # than a third of the progress sync made
    assert sync_loss < 0.8 * start_loss
    assert ssp_loss < sync_loss + 0.35 * (start_loss - sync_loss), \
        f"ssp {ssp_loss} vs sync {sync_loss}"


def test_ssp_topk_composition(mesh, lenet_net, rng_np):
    """SSP + TOPK (the SSPAggr pairing): deltas are compressed at sync
    boundaries, residuals carry error feedback, replicas stay consistent."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    cc = CommConfig(default_strategy="topk", topk_fraction=0.1)
    w0 = np.asarray(params["conv1"]["w"])  # copy before donation eats params
    ts = build_ssp_train_step(lenet_net, sp, mesh, staleness=1, comm=cc)
    st = init_ssp_state(params, N_DEV, cc)
    assert "conv1" in st.comm_error
    for i in range(1, 5):
        st, m = ts.step(st, batch, jax.random.PRNGKey(i))
        local = np.asarray(st.local_params["conv1"]["w"])
        if i % 2 == 0:  # sync point: replicas identical again
            assert np.abs(local - local[0:1]).max() == 0.0, f"iter {i}"
    # error feedback holds the unsent delta mass (non-zero after a sync)
    err = np.asarray(st.comm_error["conv1"]["w"])
    assert np.abs(err).max() > 0
    assert np.isfinite(float(m["loss"]))
    # params moved
    assert np.abs(np.asarray(st.anchor_params["conv1"]["w"]) - w0).max() > 0


def test_ssp_rejects_sfb(mesh, lenet_net):
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed")
    cc = CommConfig(layer_strategies={"ip1": SFB})
    with pytest.raises(ValueError, match="SFB"):
        build_ssp_train_step(lenet_net, sp, mesh, staleness=1, comm=cc)


# --------------------------------------------------------------------------- #
# Two-tier (ici x dcn) mesh: dense intra-slice + managed comm inter-slice
# --------------------------------------------------------------------------- #

@pytest.fixture(scope="module")
def two_tier_mesh():
    return make_mesh(axes=("dcn", "data"), shape=(2, 4))


def _two_tier_cc(**kw):
    return CommConfig(dcn_axis="dcn", **kw)


def test_two_tier_dense_matches_flat(mesh, two_tier_mesh, lenet_net, rng_np):
    """Dense sync over a (2,4) mesh == dense sync over the flat 8-mesh:
    psum over both axes touches the same 8 gradients."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)

    flat = build_train_step(lenet_net, sp, mesh, CommConfig(), donate=False)
    tier = build_train_step(lenet_net, sp, two_tier_mesh, _two_tier_cc(),
                            donate=False)
    p1, s1, m1 = flat.step(params, init_train_state(params), batch,
                           jax.random.PRNGKey(7))
    p2, s2, m2 = tier.step(params, init_train_state(params), batch,
                           jax.random.PRNGKey(7))
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-5)
    for l in p1:
        for k in p1[l]:
            np.testing.assert_allclose(
                np.asarray(p1[l][k]), np.asarray(p2[l][k]),
                rtol=1e-4, atol=1e-6, err_msg=f"{l}/{k}")


def test_two_tier_sfb_matches_dense(two_tier_mesh, lenet_net, rng_np):
    """SFB factor gathers ride both axes: bit-comparable to two-tier dense."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    dense = build_train_step(lenet_net, sp, two_tier_mesh, _two_tier_cc(),
                             donate=False)
    sfb = build_train_step(
        lenet_net, sp, two_tier_mesh,
        _two_tier_cc(layer_strategies={"ip1": SFB, "ip2": SFB}),
        donate=False)
    p1, _, m1 = dense.step(params, init_train_state(params), batch,
                           jax.random.PRNGKey(7))
    p2, _, m2 = sfb.step(params, init_train_state(params), batch,
                         jax.random.PRNGKey(7))
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-6)
    for l in p1:
        for k in p1[l]:
            np.testing.assert_allclose(
                np.asarray(p1[l][k]), np.asarray(p2[l][k]),
                rtol=1e-4, atol=1e-7, err_msg=f"{l}/{k}")


def test_two_tier_topk_consistent_and_converges(two_tier_mesh, lenet_net,
                                                rng_np):
    """Hierarchical managed comm: dense intra-slice psum + TOPK inter-slice.
    Params stay replicated across ALL devices (both slices applied the same
    compressed exchange), residuals are per-slice, and training converges."""
    from poseidon_tpu.parallel import comm_error_groups
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    w0 = np.asarray(params["conv1"]["w"])
    batch = _global_batch(rng_np)
    cc = _two_tier_cc(default_strategy="topk", topk_fraction=0.25)
    groups = comm_error_groups(cc, two_tier_mesh)
    assert groups == 2  # one residual per slice, not per device
    ts = build_train_step(lenet_net, sp, two_tier_mesh, cc, donate=False)
    p, s = params, init_train_state(params, cc, groups)
    losses = []
    for i in range(12):
        p, s, m = ts.step(p, s, batch, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    # replicas consistent: out_specs P() would fail to rebuild a replicated
    # array if devices disagreed; also check values are finite and moved
    w = np.asarray(p["conv1"]["w"])
    assert np.isfinite(w).all() and np.abs(w - w0).max() > 0
    # per-slice residuals differ (slices saw different data) and are nonzero
    err = np.asarray(s.comm_error["conv1"]["w"])
    assert err.shape[0] == 2
    assert np.abs(err).max() > 0
    assert np.abs(err[0] - err[1]).max() > 0
    # error feedback preserves convergence despite 75% of entries delayed
    assert losses[-1] < 0.5 * losses[0], losses


def test_two_tier_engine_end_to_end(tmp_path_factory, rng_np):
    """Engine + two-tier mesh: the --dcn_slices path."""
    from poseidon_tpu.runtime.engine import Engine

    tmp_path = tmp_path_factory.mktemp("two_tier")
    from tests.test_runtime import _memory_data, _write_mnistish_prototxt
    from poseidon_tpu.proto.messages import load_solver
    solver_path = _write_mnistish_prototxt(tmp_path, max_iter=25)
    sp = load_solver(solver_path)
    mesh = make_mesh(axes=("dcn", "data"), shape=(2, 4))
    cc = _two_tier_cc(default_strategy="topk", topk_fraction=0.25)
    eng = Engine(sp, comm=cc, mesh=mesh, memory_data=_memory_data(),
                 output_dir=str(tmp_path))
    try:
        last = eng.train()
        assert last["loss"] < 0.6, f"two-tier did not converge: {last}"
        out = eng.test(0)
        assert out["accuracy"] > 0.8
    finally:
        eng.close()


@pytest.mark.parametrize("policy", ["magnitude", "random", "fixed_order"])
def test_topk_policies(mesh, lenet_net, rng_np, policy):
    """UpdateSortPolicy parity (configs.hpp:27-33): every selection policy
    keeps replicas consistent, populates residuals, and still trains."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    cc = CommConfig(default_strategy="topk", topk_fraction=0.1,
                    topk_policy=policy)
    ts = build_train_step(lenet_net, sp, mesh, cc, donate=False)
    p, s = params, init_train_state(params, cc, N_DEV)
    losses = []
    for i in range(6):
        p, s, m = ts.step(p, s, batch, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses  # still learning under the budget
    assert np.abs(np.asarray(s.comm_error["conv1"]["w"])).max() > 0


def test_topk_fixed_order_covers_all_entries():
    """fixed_order rotation sends every entry exactly once per cycle."""
    from poseidon_tpu.parallel.strategies import topk_compress
    g = jnp.arange(1.0, 11.0)
    err = jnp.zeros(10)
    seen = np.zeros(10, bool)
    for step in range(5):  # fraction 0.2 -> slabs of 2 -> 5-step cycle
        sent, err_new = topk_compress(g, 0.2, jnp.zeros(10),
                                      "fixed_order", step)
        nz = np.asarray(sent) != 0
        assert nz.sum() == 2
        assert not (seen & nz).any()  # no entry twice in a cycle
        seen |= nz
    assert seen.all()


def test_bandwidth_budget_derives_topk_fraction(lenet_net):
    from poseidon_tpu.parallel.strategies import budget_topk_fraction
    cc = CommConfig(default_strategy="topk", bandwidth_budget_mb=0.1)
    frac = budget_topk_fraction(lenet_net, cc)
    total = lenet_net.param_count()
    assert frac == pytest.approx(0.1e6 / 8.0 / total, rel=1e-6)
    # no budget -> configured fraction
    assert budget_topk_fraction(lenet_net, CommConfig()) == 0.01


# --------------------------------------------------------------------------- #
# Reduced-precision wire (DenseRowFloat16 analog) + blocked top-k
# --------------------------------------------------------------------------- #

def test_wire_dtype_bf16_converges_close_to_f32(mesh, lenet_net, rng_np):
    """bf16 gradient exchange must track full-precision training closely —
    the DenseRowFloat16 trade (dense_row_float16.hpp:10-16), compiled."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    n_iters = 10

    f32 = build_train_step(lenet_net, sp, mesh, CommConfig(), donate=False)
    p1, s1 = params, init_train_state(params)
    for i in range(n_iters):
        p1, s1, m1 = f32.step(p1, s1, batch, jax.random.PRNGKey(i))

    cc = CommConfig(wire_dtype="bf16")
    bw = build_train_step(lenet_net, sp, mesh, cc, donate=False)
    p2, s2 = params, init_train_state(params, cc, N_DEV)
    for i in range(n_iters):
        p2, s2, m2 = bw.step(p2, s2, batch, jax.random.PRNGKey(i))

    start = float(np.log(10))
    l1, l2 = float(m1["loss"]), float(m2["loss"])
    assert l1 < 0.7 * start
    # within a third of full-precision progress despite half-width wire
    assert l2 < l1 + 0.33 * (start - l1), f"bf16 wire {l2} vs f32 {l1}"
    for l in p1:
        for k in p1[l]:
            np.testing.assert_allclose(
                np.asarray(p1[l][k]), np.asarray(p2[l][k]),
                rtol=0.1, atol=5e-3, err_msg=f"{l}/{k}")


def test_wire_dtype_lowers_bf16_collectives(mesh, lenet_net, rng_np):
    """The compiled step must actually carry bf16 operands into the
    collectives (not cast after): check the lowered module text."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed")
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    cc = CommConfig(wire_dtype="bf16")
    ts = build_train_step(lenet_net, sp, mesh, cc, donate=False)
    state = init_train_state(params, cc, N_DEV)
    text = ts.lowerable.lower(params, state, batch,
                              jax.random.PRNGKey(0)).as_text()
    assert "bf16" in text
    # the f32 build has no bf16 anywhere (compute dtype is f32 in tests)
    ts0 = build_train_step(lenet_net, sp, mesh, CommConfig(), donate=False)
    t0 = ts0.lowerable.lower(params, init_train_state(params), batch,
                             jax.random.PRNGKey(0)).as_text()
    assert "bf16" not in t0


def test_wire_dtype_sfb_and_topk(mesh, lenet_net, rng_np):
    """wire_dtype composes with SFB (factors gathered at bf16) and TOPK
    (values quantized into the error-feedback residual)."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    cc = CommConfig(wire_dtype="bf16",
                    layer_strategies={"ip1": SFB, "ip2": SFB,
                                      "conv1": "topk", "conv2": "topk"},
                    topk_fraction=0.2)
    ts = build_train_step(lenet_net, sp, mesh, cc, donate=False)
    p, s = params, init_train_state(params, cc, N_DEV)
    losses = []
    for i in range(8):
        p, s, m = ts.step(p, s, batch, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_wire_dtype_ssp(mesh, lenet_net, rng_np):
    """wire_dtype applies to the SSP delta exchange at sync boundaries."""
    from poseidon_tpu.parallel import build_ssp_train_step, init_ssp_state
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    cc = CommConfig(wire_dtype="bf16")
    ts = build_ssp_train_step(lenet_net, sp, mesh, staleness=1, comm=cc)
    s = init_ssp_state(params, N_DEV, cc)
    losses = []
    for i in range(8):
        s, m = ts.step(s, batch, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_blocked_topk_matches_global_budget():
    """Blocked selection keeps >= the global-k budget, selects the per-block
    maxima, and feeds the complement into the residual."""
    from poseidon_tpu.parallel.strategies import topk_compress
    rng = np.random.RandomState(0)
    g = jnp.asarray(rng.randn(1000).astype(np.float32))
    err = jnp.zeros(1000, jnp.float32)
    sent, resid = topk_compress(g, 0.01, err, "magnitude", block=100)
    nz = np.asarray(sent) != 0
    # ceil(10/10) = 1 per block x 10 blocks = 10 entries
    assert nz.sum() == 10
    # each block's winner is that block's max-|g| entry
    ga = np.asarray(g).reshape(10, 100)
    for b in range(10):
        w = np.abs(ga[b]).argmax()
        assert nz.reshape(10, 100)[b, w]
    np.testing.assert_allclose(np.asarray(sent + resid), np.asarray(g),
                               rtol=1e-6)


def test_blocked_topk_nondivisible_and_training(mesh, lenet_net, rng_np):
    """Padding path (size not a multiple of block) + end-to-end training."""
    from poseidon_tpu.parallel.strategies import topk_compress
    g = jnp.asarray(np.random.RandomState(1).randn(103).astype(np.float32))
    sent, resid = topk_compress(g, 0.1, jnp.zeros(103), "magnitude",
                                block=25)
    np.testing.assert_allclose(np.asarray(sent + resid), np.asarray(g),
                               rtol=1e-6)
    assert (np.asarray(sent) != 0).sum() >= 10

    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    cc = CommConfig(default_strategy="topk", topk_fraction=0.1,
                    topk_block=256)
    ts = build_train_step(lenet_net, sp, mesh, cc, donate=False)
    p, s = params, init_train_state(params, cc, N_DEV)
    losses = []
    for i in range(10):
        p, s, m = ts.step(p, s, batch, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_random_topk_decorrelated_across_layers():
    """Same-shaped tensors in different layers must select different random
    subsets (the per-table independence of the reference's Random policy)."""
    from poseidon_tpu.parallel.strategies import comm_salt, topk_compress
    g = jnp.ones(1000)
    err = jnp.zeros(1000)
    s1, _ = topk_compress(g, 0.05, err, "random", step=3,
                          salt=comm_salt("conv1", "w"))
    s2, _ = topk_compress(g, 0.05, err, "random", step=3,
                          salt=comm_salt("conv2", "w"))
    nz1 = np.flatnonzero(np.asarray(s1))
    nz2 = np.flatnonzero(np.asarray(s2))
    assert not np.array_equal(nz1, nz2)


# --------------------------------------------------------------------------- #
# SSP x two-tier mesh: staleness on the DCN tier, dense ICI tier every step
# (the SSPAggr deployment: full-rate intra-machine, managed inter-machine)
# --------------------------------------------------------------------------- #

def test_ssp_two_tier_slices_sync_on_boundary(two_tier_mesh, lenet_net,
                                              rng_np):
    """With staleness on the DCN tier, the two slices diverge between syncs
    and reconcile exactly at the boundary; devices inside a slice see the
    same slice-local params throughout."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    cc = _two_tier_cc()
    ts = build_ssp_train_step(lenet_net, sp, two_tier_mesh, staleness=1,
                              comm=cc)
    st = init_ssp_state(params, 2, cc)  # 2 slices
    for i in range(1, 5):
        st, m = ts.step(st, batch, jax.random.PRNGKey(i))
        local = np.asarray(st.local_params["conv1"]["w"])  # (2, ...)
        diverged = np.abs(local[0] - local[1]).max()
        if i % 2 == 0:  # sync boundary: slices reconciled
            assert diverged == 0.0, f"iter {i}: slices differ by {diverged}"
        else:           # mid-period: slices have diverged (different shards)
            assert diverged > 0.0, f"iter {i}: slices did not diverge"
    assert np.isfinite(float(m["loss"]))


def test_ssp_two_tier_with_sfb_and_topk(two_tier_mesh, lenet_net, rng_np):
    """The full SSPAggr composition: SFB FC layers ride the per-step ICI
    tier, conv layers TOPK-compress their deltas across the DCN tier, all
    under staleness 1 — and training still converges."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    cc = _two_tier_cc(layer_strategies={"ip1": SFB, "ip2": SFB,
                                        "conv1": "topk", "conv2": "topk"},
                      topk_fraction=0.2)
    ts = build_ssp_train_step(lenet_net, sp, two_tier_mesh, staleness=1,
                              comm=cc)
    st = init_ssp_state(params, 2, cc)
    assert "conv1" in st.comm_error and "ip1" not in st.comm_error
    losses = []
    for i in range(10):
        st, m = ts.step(st, batch, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
    # TOPK residuals hold unsent delta mass after a sync
    assert np.abs(np.asarray(st.comm_error["conv1"]["w"])).max() > 0


def test_ssp_two_tier_staleness0_matches_sync(two_tier_mesh, lenet_net,
                                              rng_np):
    """staleness=0 over the two-tier mesh must equal the fully-synchronous
    two-tier step: every step reconciles, so no divergence survives."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    cc = _two_tier_cc()
    sync = build_train_step(lenet_net, sp, two_tier_mesh, cc, donate=False)
    p1, s1 = params, init_train_state(params, cc, 2)
    ssp = build_ssp_train_step(lenet_net, sp, two_tier_mesh, staleness=0,
                               comm=cc)
    st = init_ssp_state(params, 2, cc)
    for i in range(3):
        p1, s1, m1 = sync.step(p1, s1, batch, jax.random.PRNGKey(9))
        st, m2 = ssp.step(st, batch, jax.random.PRNGKey(9))
    assert float(m1["loss"]) == pytest.approx(float(m2["loss"]), rel=1e-4)
    for l in p1:
        for k in p1[l]:
            np.testing.assert_allclose(
                np.asarray(p1[l][k]), np.asarray(st.anchor_params[l][k]),
                rtol=1e-3, atol=1e-5, err_msg=f"{l}/{k}")


def test_ssp_resume_across_topologies(mesh, two_tier_mesh, lenet_net,
                                      rng_np):
    """A flat-mesh SSP snapshot (8 per-device groups) resumes onto the
    two-tier mesh (2 per-slice groups): coerce_state re-seeds the local
    replicas from the anchor at the stored iteration."""
    from poseidon_tpu.runtime.checkpoint import coerce_state
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)

    flat_cc = CommConfig()
    ts = build_ssp_train_step(lenet_net, sp, mesh, staleness=1, comm=flat_cc)
    st = init_ssp_state(params, N_DEV, flat_cc)
    for i in range(4):
        st, _ = ts.step(st, batch, jax.random.PRNGKey(i))

    tt_cc = _two_tier_cc(default_strategy="topk", topk_fraction=0.2)
    p2, st2 = coerce_state(st.anchor_params, st, staleness=1, n_dev=2,
                           comm=tt_cc)
    assert jax.tree_util.tree_leaves(st2.local_params)[0].shape[0] == 2
    assert int(st2.it) == 4  # iteration survives the topology change
    ts2 = build_ssp_train_step(lenet_net, sp, two_tier_mesh, staleness=1,
                               comm=tt_cc)
    losses = []
    for i in range(4):
        st2, m = ts2.step(st2, batch, jax.random.PRNGKey(10 + i))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] + 0.05  # keeps converging after resume


def test_blocked_topk_honors_budget_from_below():
    """The blocked path never exceeds the k budget; when k < n_blocks it
    falls back to exact global selection (budget contract, SSPAggr's
    bandwidth bound)."""
    from poseidon_tpu.parallel.strategies import topk_compress
    rng = np.random.RandomState(0)
    g = jnp.asarray(rng.randn(10000).astype(np.float32))
    err = jnp.zeros(10000, jnp.float32)
    # k = 100, blocks of 100 -> 100 blocks, kb = 1 -> exactly 100 sent
    sent, _ = topk_compress(g, 0.01, err, "magnitude", block=100)
    assert (np.asarray(sent) != 0).sum() == 100
    # k = 10 < 100 blocks -> global fallback, exactly 10 sent (not 100)
    sent2, _ = topk_compress(g, 0.001, err, "magnitude", block=100)
    assert (np.asarray(sent2) != 0).sum() == 10
    # global fallback picks the true global top-10
    top10 = np.argsort(-np.abs(np.asarray(g)))[:10]
    assert set(np.flatnonzero(np.asarray(sent2))) == set(top10)


def test_wire_dtype_f16_converges(mesh, lenet_net, rng_np):
    """f16 wire (the reference's actual DenseRowFloat16 dtype): narrower
    exponent than bf16, still converges at LeNet scale with mean reduce
    (overflow at extreme device counts is the documented trade)."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    batch = _global_batch(rng_np)
    cc = CommConfig(wire_dtype="f16")
    ts = build_train_step(lenet_net, sp, mesh, cc, donate=False)
    p, s = params, init_train_state(params, cc, N_DEV)
    losses = []
    for i in range(8):
        p, s, m = ts.step(p, s, batch, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < 0.8 * losses[0], losses
