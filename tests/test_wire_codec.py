"""Zero-copy tensor wire codec + error-feedback compressed deltas (ISSUE 18).

The DCN tier's hot path used to pay pickle both ways: a full serialize copy
on send and a parse copy on receive, for payloads that are almost entirely
raw tensor bytes. The codec ships a tag-encoded metadata skeleton plus the
tensors' own buffers (scatter-gather send, preallocated receive), and the
wire-dtype compressor halves/quarters those bytes with EXACT error feedback
riding the managed-communication residual. These tests pin the contracts
that make both safe:

1. fidelity — every supported leaf (all ndarray dtypes incl. bfloat16,
   0-d/empty/non-contiguous arrays, nested trees, TOPK/q8 tuples, scalars,
   str/bytes) roundtrips bitwise through encode/decode and the socket path;
2. containment — truncated AND oversized frames raise FrameError (never a
   silent pad/drop), and a lying length prefix is rejected BEFORE the
   payload buffer is allocated (max_frame_bytes cap);
3. compatibility — codec off (or an un-negotiated peer) is byte-for-byte
   today's pickle wire; unsupported objects fall back to pickle per frame;
4. exactness — ``sent + residual == update`` holds bitwise for every wire
   dtype, dense and TOPK, so codec-on dense f32 equals the pickle path and
   a bf16-wire 2-worker run is bitwise identical to dense at every gate
   (power-of-two deltas, the managed-comm idiom).

Every socket binds port 0 on loopback — no fixed ports, no flakes.
"""

import pickle
import socket
import struct
import threading

import numpy as np
import pytest

from poseidon_tpu.parallel.async_ssp import (AsyncSSPClient, ParamService,
                                             _dense_f32, _quantize_leaf,
                                             _quantize_tree,
                                             resolve_wire_dtype, split_topk)
from poseidon_tpu.proto import wire
from poseidon_tpu.proto.wire import (CODEC_MAGIC, FrameError,
                                     FrameTooLargeError,
                                     decode_codec_payload,
                                     encode_codec_payload, mark_codec_socket,
                                     recv_frame_sized, send_frame,
                                     set_max_frame_bytes, set_wire_codec,
                                     socket_uses_codec, wire_stats)


@pytest.fixture(autouse=True)
def _restore_wire_globals():
    yield
    set_wire_codec(None)
    set_max_frame_bytes(None)


def _codec_roundtrip(obj):
    enc = encode_codec_payload(obj)
    assert enc is not None, f"codec refused {type(obj)}"
    parts, n = enc
    flat = b"".join(bytes(p) for p in parts)
    assert len(flat) == n
    return decode_codec_payload(flat)


def _assert_leaf_equal(a, b):
    if isinstance(a, np.ndarray):
        assert isinstance(b, np.ndarray)
        assert a.dtype == b.dtype and a.shape == b.shape
        assert np.ascontiguousarray(a).tobytes() == b.tobytes()
    elif isinstance(a, (list, tuple)):
        assert type(a) is type(b) and len(a) == len(b)
        for x, y in zip(a, b):
            _assert_leaf_equal(x, y)
    elif isinstance(a, dict):
        assert isinstance(b, dict) and set(a) == set(b)
        for k in a:
            _assert_leaf_equal(a[k], b[k])
    elif isinstance(a, np.generic):
        assert type(a) is type(b) and a.tobytes() == b.tobytes()
    elif isinstance(a, float) and a != a:          # NaN payloads survive
        assert b != b
    else:
        assert type(a) is type(b) and a == b


# --------------------------------------------------------------------------- #
# 1. fidelity: roundtrip fuzz
# --------------------------------------------------------------------------- #

ALL_DTYPES = ["float32", "float64", "float16", "bfloat16", "int8", "uint8",
              "int16", "int32", "int64", "uint32", "bool"]


def _make(dtype_name: str, shape, rng):
    import ml_dtypes  # noqa: F401 — registers bfloat16 with numpy
    dt = np.dtype(dtype_name)
    if dt == np.bool_:
        return np.asarray(rng.rand(*shape) > 0.5)
    if np.issubdtype(dt, np.integer):
        info = np.iinfo(dt)
        return np.asarray(rng.randint(max(info.min, -1000),
                                      min(info.max, 1000) + 1,
                                      size=shape)).astype(dt)
    return np.asarray(rng.randn(*shape) * 3).astype(dt)


@pytest.mark.parametrize("dtype_name", ALL_DTYPES)
def test_roundtrip_every_dtype_bitwise(dtype_name):
    rng = np.random.RandomState(7)
    for shape in [(5,), (3, 4), (2, 3, 4), (1,), (16, 16)]:
        a = _make(dtype_name, shape, rng)
        _assert_leaf_equal(a, _codec_roundtrip(a))


def test_roundtrip_degenerate_arrays():
    """0-d, empty, and non-contiguous leaves all survive; non-contiguous
    comes back compacted (C order) with identical values."""
    zero_d = np.float32(3.25) + np.zeros((), np.float32)
    empty = np.zeros((0, 3), np.float32)
    base = np.arange(24, dtype=np.float32).reshape(4, 6)
    strided = base[::2, ::3]
    transposed = base.T
    for a in (zero_d, empty, strided, transposed):
        b = _codec_roundtrip(a)
        assert b.dtype == a.dtype and b.shape == a.shape
        np.testing.assert_array_equal(np.asarray(a), b)
    # the decoded copy of a non-contiguous source is contiguous
    assert _codec_roundtrip(strided).flags["C_CONTIGUOUS"]


def test_roundtrip_nested_trees_and_topk_leaves():
    rng = np.random.RandomState(3)
    vals = rng.randn(7).astype(np.float32)
    idx = np.array([1, 5, 9, 2, 44, 3, 0], np.int64)
    msg = {
        "kind": "push", "worker": 3, "clock": 12, "seq": None,
        "ok": True, "frac": 0.25, "tag": b"\x00raw\xff", "name": "fc1/w",
        "delta": {
            "fc": {"w": rng.randn(4, 4).astype(np.float32),
                   "b": ("topk", idx, vals)},
            "conv": {"w": ("topk", idx[:3],
                           ("q8", np.float32(0.125),
                            np.array([1, -7, 127], np.int8)))},
        },
        "clocks": [0, 1, 2], "pair": (1, 2),
        "scalar": np.float32(1.5),
    }
    _assert_leaf_equal(msg, _codec_roundtrip(msg))


def test_roundtrip_fuzz_random_trees():
    """Structured fuzz: 40 random nested trees mixing every supported
    leaf kind, each roundtripped bitwise."""
    rng = np.random.RandomState(1234)

    def leaf(depth):
        r = rng.randint(0, 10)
        if r == 0:
            return None
        if r == 1:
            return bool(rng.randint(2))
        if r == 2:
            return int(rng.randint(-10**12, 10**12))
        if r == 3:
            return float(rng.randn())
        if r == 4:
            return "s" * rng.randint(0, 9) + "π"
        if r == 5:
            return bytes(rng.randint(0, 256, size=rng.randint(0, 16))
                         .astype(np.uint8).tobytes())
        dt = ALL_DTYPES[rng.randint(len(ALL_DTYPES))]
        shape = tuple(rng.randint(0, 5)
                      for _ in range(rng.randint(0, 3)))
        return _make(dt, shape, rng)

    def tree(depth):
        if depth >= 3 or rng.rand() < 0.3:
            return leaf(depth)
        r = rng.randint(3)
        n = rng.randint(0, 4)
        if r == 0:
            return [tree(depth + 1) for _ in range(n)]
        if r == 1:
            return tuple(tree(depth + 1) for _ in range(n))
        return {f"k{i}": tree(depth + 1) for i in range(n)}

    for _ in range(40):
        t = tree(0)
        _assert_leaf_equal(t, _codec_roundtrip(t))


def test_skeleton_depth_limit_falls_back_to_pickle():
    deep = [1]
    for _ in range(80):
        deep = [deep]
    assert encode_codec_payload(deep) is None      # caller pickles instead


def test_unsupported_objects_fall_back_to_pickle():
    for obj in ({1, 2, 3}, object(), {"x": {4: "non-str-key-ok"}},
                np.ma.masked_array([1.0])):
        enc = encode_codec_payload(obj)
        if enc is not None:                        # dicts with int keys ARE
            _assert_leaf_equal(obj, _codec_roundtrip(obj))   # supported


# --------------------------------------------------------------------------- #
# 2. containment: truncation, oversize, cap
# --------------------------------------------------------------------------- #

def _encode_flat(obj) -> bytes:
    parts, n = encode_codec_payload(obj)
    return b"".join(bytes(p) for p in parts)


def test_truncated_payload_rejected_at_every_cut():
    flat = _encode_flat({"fc": np.arange(12, dtype=np.float32)})
    for cut in list(range(0, 12)) + [len(flat) - 7, len(flat) - 1]:
        with pytest.raises(FrameError):
            decode_codec_payload(flat[:cut])


def test_oversized_payload_rejected():
    flat = _encode_flat({"fc": np.arange(12, dtype=np.float32)})
    with pytest.raises(FrameError, match="size mismatch|trailing"):
        decode_codec_payload(flat + b"\x00\x00\x00\x00")


def test_lying_skeleton_extents_rejected():
    # skeleton claims more tensor bytes than the frame carries
    flat = bytearray(_encode_flat(np.zeros(4, np.float32)))
    # ndarray dim is a !Q at the end of the skeleton; inflate it
    (skel_len,) = struct.unpack("!I", flat[4:8])
    dim_off = 8 + skel_len - 8
    flat[dim_off:8 + skel_len] = struct.pack("!Q", 1 << 40)
    with pytest.raises(FrameError):
        decode_codec_payload(bytes(flat))


def test_header_over_cap_rejected_before_allocation():
    """A lying length prefix is refused from the 8-byte header alone —
    the receiver never allocates (or reads) a payload over the cap."""
    a, b = socket.socketpair()
    try:
        set_max_frame_bytes(4096)
        a.sendall(struct.pack("!Q", 1 << 33))      # 8 GiB claim, no payload
        with pytest.raises(FrameError, match="exceeds cap"):
            recv_frame_sized(b)
    finally:
        a.close()
        b.close()


def test_send_over_cap_refused_loudly():
    a, b = socket.socketpair()
    try:
        set_max_frame_bytes(1024)
        mark_codec_socket(a)
        with pytest.raises(FrameTooLargeError):
            send_frame(a, np.zeros(4096, np.float32))   # codec path
        with pytest.raises(FrameTooLargeError):
            send_frame(a, np.zeros(4096, np.float32), codec=False)
    finally:
        a.close()
        b.close()


# --------------------------------------------------------------------------- #
# 3. compatibility: pickle byte-identity + per-socket negotiation state
# --------------------------------------------------------------------------- #

def _wire_bytes(obj, codec_marked: bool, codec_global: bool) -> bytes:
    a, b = socket.socketpair()
    try:
        set_wire_codec(codec_global)
        if codec_marked:
            mark_codec_socket(a)
        n = send_frame(a, obj)
        a.shutdown(socket.SHUT_WR)
        got = b.makefile("rb").read()
        assert len(got) == n
        return got
    finally:
        set_wire_codec(None)
        a.close()
        b.close()


def test_codec_off_is_byte_identical_to_pickle():
    obj = {"kind": "push", "delta": {"fc": np.arange(6, dtype=np.float32)}}
    want = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    want = struct.pack("!Q", len(want)) + want
    # kill switch off: even a negotiated socket speaks pickle
    assert _wire_bytes(obj, codec_marked=True, codec_global=False) == want
    # un-negotiated socket with the codec on: pickle, byte for byte
    assert _wire_bytes(obj, codec_marked=False, codec_global=True) == want


def test_codec_frames_flow_only_on_marked_sockets():
    obj = {"x": np.arange(4, dtype=np.float32)}
    raw = _wire_bytes(obj, codec_marked=True, codec_global=True)
    assert raw[8:12] == CODEC_MAGIC
    raw = _wire_bytes(obj, codec_marked=False, codec_global=True)
    assert raw[8:9] == b"\x80"                     # pickle protocol marker


def test_socket_roundtrip_codec_and_pickle_interleaved():
    """One connection carrying codec frames, a pickle fallback frame
    (unsupported object), and codec again — the receiver auto-detects per
    frame, no state desync."""
    a, b = socket.socketpair()
    mark_codec_socket(a)
    msgs = [{"d": np.arange(9, dtype=np.float32).reshape(3, 3)},
            {"oops": {1, 2, 3}},                   # set -> pickle fallback
            {"t": ("topk", np.array([0, 2], np.int64),
                   np.array([1.5, -2.5], np.float32))}]
    got = []

    def rx():
        for _ in msgs:
            got.append(recv_frame_sized(b)[0])

    t = threading.Thread(target=rx)
    t.start()
    try:
        for m in msgs:
            send_frame(a, m)
        t.join(timeout=10.0)
        assert not t.is_alive()
        for m, g in zip(msgs, got):
            _assert_leaf_equal(m, g)
    finally:
        a.close()
        b.close()


def test_decoded_arrays_are_writable_views():
    """The zero-copy contract: decoded arrays alias the per-frame receive
    buffer and are WRITABLE (the apply path adds into them in place)."""
    a, b = socket.socketpair()
    mark_codec_socket(a)
    try:
        send_frame(a, {"w": np.arange(8, dtype=np.float32)})
        obj, _ = recv_frame_sized(b)
        obj["w"] += 1.0                            # must not raise
        np.testing.assert_array_equal(
            obj["w"], np.arange(8, dtype=np.float32) + 1.0)
    finally:
        a.close()
        b.close()


# --------------------------------------------------------------------------- #
# 4. exactness: error feedback + bitwise parity with the pickle path
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("wd", ["bf16", "f16", "int8"])
def test_sent_plus_residual_reassembles_update_exactly(wd):
    """The PR-12 invariant extended to every wire dtype: dequant(sent) +
    residual == update, BITWISE, including zeros, denormal-range values
    and f16-overflow magnitudes."""
    rng = np.random.RandomState(5)
    v = (rng.randn(4096).astype(np.float32) *
         np.float32(10.0) ** rng.randint(-12, 10, size=4096)).astype(
             np.float32)
    v[:8] = [0.0, 1e-38, -1e-38, 7.125, -7.125, 1e30, -1e30, 65504.0]
    leaf, residual, nbytes = _quantize_leaf(v.copy(), wd)
    back = _dense_f32(leaf)
    if residual is None:
        residual = np.zeros_like(v)
    re = back + residual
    assert re.dtype == np.float32
    np.testing.assert_array_equal(re, v)
    assert nbytes < v.nbytes                       # compression is real


@pytest.mark.parametrize("wd", ["bf16", "f16", "int8"])
def test_quantize_tree_topk_exactness(wd):
    """TOPK partials compress their VALUES too, and the quantization error
    folds into exactly the selected entries' residual slots."""
    rng = np.random.RandomState(9)
    tree = {"fc": {"w": rng.randn(32, 32).astype(np.float32)}}
    sent, kept, n_sent, n_total = split_topk(tree, 0.25)
    assert 0 < n_sent < n_total
    idx, vals = sent["fc"]["w"][1], sent["fc"]["w"][2]
    leaf, err, nbytes = _quantize_leaf(vals.copy(), wd)
    back = _dense_f32(leaf)
    if err is None:
        err = np.zeros_like(vals)
    np.testing.assert_array_equal(back + err, vals)


def test_quantize_tree_pow2_is_residual_free():
    """Powers of two are exact in bf16 — the quantizer detects a lossless
    pass and returns residual=None (nothing to carry)."""
    tree = {"fc": {"w": (2.0 ** -(np.arange(16.0) % 6))
                   .astype(np.float32).reshape(4, 4)}}
    wt, residual, saved = _quantize_tree(tree, "bf16")
    assert residual is None
    assert saved > 0
    np.testing.assert_array_equal(_dense_f32(wt["fc"]["w"]),
                                  tree["fc"]["w"])


def _zeros(shape=(4, 4)):
    return {"fc": {"w": np.zeros(shape, np.float32)}}


def _pow2_delta(worker: int, clock: int, shape=(4, 4)):
    n = int(np.prod(shape))
    exps = -(np.arange(n) % 6) - clock - 8 * worker
    return {"fc": {"w": (2.0 ** exps).astype(np.float32).reshape(shape)}}


def test_codec_on_dense_f32_equals_pickle_path_bitwise():
    """The tentpole pin: the SAME dense f32 push stream through a codec
    session and a pickle (codec-off) session produces bitwise-identical
    anchors — the codec changes bytes-on-wire, never values."""
    deltas = [_pow2_delta(0, c) for c in range(4)]

    def run(codec_on: bool):
        set_wire_codec(codec_on)
        svc = ParamService(_zeros(), n_workers=1)
        cli = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=0,
                             n_workers=1)
        try:
            for c, d in enumerate(deltas):
                cli.push(d)
                cli.gate(c + 1)
            cli._drain()
            # Negotiation state is per-socket, not a global counter, so a
            # lingering handler thread from another session can't skew it.
            negotiated = socket_uses_codec(cli._push_sock)
            return svc.anchor["fc"]["w"].copy(), negotiated
        finally:
            set_wire_codec(None)
            cli.close()
            svc.close()

    a_codec, codec_negotiated = run(True)
    a_pickle, pickle_negotiated = run(False)
    assert codec_negotiated is True                # negotiation really on
    assert pickle_negotiated is False              # kill switch really off
    np.testing.assert_array_equal(a_codec, a_pickle)
    assert a_codec.tobytes() == a_pickle.tobytes()


def test_two_worker_bf16_wire_bitwise_equal_to_dense_at_gates():
    """The managed-comm acceptance test re-run under a compressed wire:
    two workers, budget-tight bf16-wire arm vs the dense f32 arm — at
    every SSP window boundary the anchor AND each worker's gate-time
    applied state are bitwise identical (power-of-two deltas are bf16-
    exact; the error-feedback residual carries everything else)."""
    n_clocks, staleness = 8, 1
    dense_svc = ParamService(_zeros(), n_workers=2)
    wire_svc = ParamService(_zeros(), n_workers=2)
    dense = [AsyncSSPClient(w, ("127.0.0.1", dense_svc.port),
                            staleness=staleness, n_workers=2)
             for w in range(2)]
    wired = []
    for w in range(2):
        cli = AsyncSSPClient(w, ("127.0.0.1", wire_svc.port),
                             staleness=staleness, n_workers=2,
                             budget_mbps=1e-6, priority_frac=0.25,
                             wire_dtype="bf16")
        cli.budget.consume(1e12)                   # deep deficit: partials
        wired.append(cli)
    try:
        for c in range(n_clocks):
            for w in range(2):
                d = _pow2_delta(w, c)
                dense[w].push(d)
                wired[w].push(d)
            for w in range(2):
                dense[w]._drain()
                wired[w]._drain()
            if (c + 1) % (staleness + 1) == 0:     # window boundary
                assert np.array_equal(dense_svc.anchor["fc"]["w"],
                                      wire_svc.anchor["fc"]["w"]), c
                assert (dense_svc.anchor["fc"]["w"].tobytes()
                        == wire_svc.anchor["fc"]["w"].tobytes())
                for w in range(2):
                    cache_d, _ = dense[w].refresh()
                    cache_m, _ = wired[w].refresh()
                    assert (cache_d["fc"]["w"].tobytes()
                            == cache_m["fc"]["w"].tobytes()), (c, w)
                    assert dense[w].gate(c + 1, timeout_s=10.0) is not None
                    assert wired[w].gate(c + 1, timeout_s=10.0) is not None
        assert all(m.partial_pushes > 0 for m in wired)
        assert all(m.wire_bytes_saved > 0 for m in wired)
    finally:
        for cli in wired + dense:
            cli.close()
        wire_svc.close()
        dense_svc.close()


def test_wire_dtype_and_adarevision_refuse_to_compose():
    svc = ParamService(_zeros(), n_workers=1, server_logic="adarevision")
    try:
        with pytest.raises(ValueError, match="adarevision"):
            AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=0,
                           n_workers=1, server_logic="adarevision",
                           wire_dtype="bf16")
    finally:
        svc.close()


def test_resolve_wire_dtype_normalization():
    assert resolve_wire_dtype("") == ""
    assert resolve_wire_dtype(None) == ""
    assert resolve_wire_dtype("f32") == ""
    assert resolve_wire_dtype("float32") == ""
    assert resolve_wire_dtype("off") == ""
    assert resolve_wire_dtype("BF16") == "bf16"
    assert resolve_wire_dtype("f16") == "f16"
    assert resolve_wire_dtype("int8") == "int8"
    with pytest.raises(ValueError):
        resolve_wire_dtype("int4")
