"""Ring attention / all-to-all sequence parallelism vs full attention."""

import functools

import jax
from poseidon_tpu.compat import shard_map
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from poseidon_tpu.ops.attention import attention
from poseidon_tpu.parallel.mesh import make_mesh
from poseidon_tpu.parallel.sequence import (ring_attention,
                                            ring_flash_attention,
                                            ulysses_attention)

N_DEV = 8
B, H, S, D = 2, 8, 64, 16  # S sharded into 8 blocks of 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(axes=("seq",))


@pytest.fixture(scope="module")
def qkv(rng_np=None):
    rs = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rs.randn(B, H, S, D).astype(np.float32) * 0.5)
    return mk(), mk(), mk()


def _sharded(mesh, fn, causal):
    wrapped = shard_map(
        functools.partial(fn, axis="seq", causal=causal),
        mesh=mesh,
        in_specs=(P(None, None, "seq"), P(None, None, "seq"),
                  P(None, None, "seq")),
        out_specs=P(None, None, "seq"),
        check_vma=False)
    return jax.jit(wrapped)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(mesh, qkv, causal):
    q, k, v = qkv
    want = attention(q, k, v, causal=causal)
    got = _sharded(mesh, ring_attention, causal)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(mesh, qkv, causal):
    q, k, v = qkv
    want = attention(q, k, v, causal=causal)
    got = _sharded(mesh, ulysses_attention, causal)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def _sharded_flash(mesh, causal, block=8):
    wrapped = shard_map(
        lambda q, k, v: ring_flash_attention(q, k, v, "seq", causal, None,
                                             block, True),
        mesh=mesh,
        in_specs=(P(None, None, "seq"), P(None, None, "seq"),
                  P(None, None, "seq")),
        out_specs=P(None, None, "seq"),
        check_vma=False)
    return jax.jit(wrapped)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_matches_full(mesh, qkv, causal):
    """Ring exchange with per-chunk Pallas flash kernels + lse merge."""
    q, k, v = qkv
    want = attention(q, k, v, causal=causal)
    got = _sharded_flash(mesh, causal)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_gradients_match(mesh, qkv, causal):
    """The ring-level custom VJP (dk/dv accumulators riding the ring) vs the
    dense reference gradients."""
    q, k, v = qkv

    def loss_full(q, k, v):
        return jnp.sum(attention(q, k, v, causal=causal) ** 2)

    ring = _sharded_flash(mesh, causal)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_full, g_ring, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=5e-3, atol=5e-4, err_msg=name)


def test_ring_attention_gradients_match(mesh, qkv):
    q, k, v = qkv

    def loss_full(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    ring = _sharded(mesh, ring_attention, True)

    def loss_ring(q, k, v):
        return jnp.sum(ring(q, k, v) ** 2)

    g_full = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_full, g_ring, "qkv"):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gf),
                                   rtol=5e-3, atol=5e-4, err_msg=name)
