"""Wait-free asynchronous SSP (the Bösen execution model) — process tier.

Closes the round-4 verdict's missing #1: the compiled SSP step reconciles at
a barrier; the reference's workers never barrier inside the staleness window
(ssp_consistency_controller.cpp:37-77). These tests pin the three properties
that define the mechanism, on real threads exchanging real bytes through the
ParamService socket protocol:

1. wait-free: with the window open, a fast worker NEVER blocks while a
   straggler sleeps (blocked_s == 0);
2. bounded: the clock spread observed at the server never exceeds s + 1;
3. convergent: async-SSP digits training lands within half a point of the
   same model trained synchronously.
"""

import json
import os
import socket
import sys
import threading

import numpy as np
import pytest

from poseidon_tpu.parallel.async_ssp import (ParamService,
                                             run_async_ssp_worker)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _zeros_params(shape=(4, 3)):
    return {"fc": {"w": np.zeros(shape, np.float32)}}


def _counting_step(worker):
    """A local_step that just adds worker-tagged ones (inspectable math)."""
    def step(params, it):
        out = {l: {p: v + 1.0 for p, v in ps.items()}
               for l, ps in params.items()}
        return out, 0.0
    return step


def _run_workers(n, staleness, n_clocks, slow_map, service, params,
                 step_fn=_counting_step, **kw):
    results = [None] * n
    errs = []

    def go(w):
        try:
            results[w] = run_async_ssp_worker(
                w, n, params, step_fn(w), n_clocks, staleness,
                service=service, slow_s=slow_map.get(w, 0.0), **kw)
        except Exception as e:  # noqa: BLE001
            errs.append((w, e))

    ts = [threading.Thread(target=go, args=(w,)) for w in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    assert not errs, errs
    return results


def test_wait_free_inside_window():
    """Window >= run length: the fast worker must finish all its clocks
    without EVER blocking, while the straggler is still asleep — the exact
    property the compiled reconcile barrier cannot provide."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=2)
    try:
        res = _run_workers(2, staleness=50, n_clocks=12,
                           slow_map={1: 0.05}, service=svc, params=params)
    finally:
        svc.close()
    fast, slow = res
    assert fast["gate_blocks"] == 0
    assert fast["blocked_s"] == 0.0
    # fast finished well before the straggler's sleep budget (12 x 50 ms)
    assert fast["wall_s"] < 0.5 * slow["wall_s"], (fast["wall_s"],
                                                  slow["wall_s"])


def test_ssp_bound_enforced():
    """s = 1: the server must never observe a clock spread beyond s + 1,
    and the fast worker must actually hit the gate (it is 20x faster)."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=2)
    try:
        res = _run_workers(2, staleness=1, n_clocks=10,
                           slow_map={1: 0.04}, service=svc, params=params)
        spread = svc.max_spread
    finally:
        svc.close()
    fast = res[0]
    assert spread <= 2, spread          # s + 1
    assert fast["gate_blocks"] > 0      # the bound did real work


def test_all_updates_arrive():
    """Additive apply: after both workers flush every clock, the anchor
    holds exactly n_workers * n_clocks increments (no lost oplogs)."""
    params = _zeros_params((2, 2))
    svc = ParamService(params, n_workers=2)
    try:
        _run_workers(2, staleness=5, n_clocks=7, slow_map={},
                     service=svc, params=params)
        # each clock each worker pushes +1 over the whole tree
        np.testing.assert_allclose(svc.anchor["fc"]["w"],
                                   np.full((2, 2), 14.0))
    finally:
        svc.close()


def test_read_my_writes_cache():
    """refresh() must rebuild anchor + own pending increments, so a
    worker's own updates are never lost from its view even while the
    server has not applied them (client cache + oplog composition,
    the reference's process storage + oplog pairing)."""
    from poseidon_tpu.parallel.async_ssp import AsyncSSPClient
    params = _zeros_params((2, 2))
    svc = ParamService(params, n_workers=2)
    cli = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=5)
    try:
        # freeze dispatch: pushes stay in the local oplog, never reach the
        # server — the exact window read-my-writes exists for
        cli._stop.set()
        cli._sender.join(timeout=5)
        one = {"fc": {"w": np.ones((2, 2), np.float32)}}
        cli.push(one)
        cli.push(one)
        cache, clocks = cli.refresh()
        np.testing.assert_allclose(cache["fc"]["w"], 2.0)  # own 2 pending
        assert clocks[0] == -1          # server never applied them
        np.testing.assert_allclose(svc.anchor["fc"]["w"], 0.0)
    finally:
        cli._acked_clock = cli.clock    # close() must not wait on the
        cli.close()                     # deliberately-frozen sender
        svc.close()


def _digits():
    from sklearn.datasets import load_digits
    X, y = load_digits(return_X_y=True)
    X = (X / 16.0).astype(np.float32)
    rs = np.random.RandomState(0)
    idx = rs.permutation(len(X))
    X, y = X[idx], y[idx]
    n_tr = 1500
    return (X[:n_tr], y[:n_tr]), (X[n_tr:], y[n_tr:])


def _softmax_step(X, y, lr=0.5, batch=128):
    """One minibatch softmax-regression SGD step on a worker's shard."""
    n = len(X)

    def step(params, it):
        rs = np.random.RandomState(it)
        sel = rs.randint(0, n, size=batch)
        xb, yb = X[sel], y[sel]
        W = params["fc"]["w"]            # (64, 10)
        logits = xb @ W
        logits -= logits.max(axis=1, keepdims=True)
        p = np.exp(logits)
        p /= p.sum(axis=1, keepdims=True)
        loss = -np.log(p[np.arange(batch), yb] + 1e-9).mean()
        p[np.arange(batch), yb] -= 1.0
        g = xb.T @ p / batch
        return {"fc": {"w": W - lr * g}}, loss
    return step


def _accuracy(W, X, y):
    return float((np.argmax(X @ W, axis=1) == y).mean())


@pytest.mark.slow
def test_digits_convergence_matches_sync():
    """2 async-SSP workers (one a straggler) on disjoint digit shards must
    land within half a point of the SAME configuration trained with zero
    staleness (BSP: both workers' updates applied additively at every
    step) — the reference's SSP quality claim (bounded staleness trades
    freshness for wait-freedom, not accuracy), tested end to end through
    the socket tier. The only variable between the two runs is staleness."""
    (Xtr, ytr), (Xte, yte) = _digits()
    n_clocks, sync_every, lr = 240, 4, 0.25
    half = len(Xtr) // 2
    shards = [(Xtr[:half], ytr[:half]), (Xtr[half:], ytr[half:])]

    # BSP baseline: same shards, same additive update structure, s = 0
    steps = [_softmax_step(*shards[w], lr=lr) for w in range(2)]
    W = np.zeros((64, 10), np.float32)
    for it in range(n_clocks * sync_every):
        upd = np.zeros_like(W)
        for w in range(2):
            new, _ = steps[w]({"fc": {"w": W.copy()}}, it)
            upd += new["fc"]["w"] - W
        W += upd
    acc_bsp = _accuracy(W, Xte, yte)

    # async: worker 1 a straggler, s = 2, wait-free inside the window
    W0 = {"fc": {"w": np.zeros((64, 10), np.float32)}}
    svc = ParamService(W0, n_workers=2)
    try:
        res = _run_workers(
            2, staleness=2, n_clocks=n_clocks, slow_map={1: 0.002},
            service=svc, params=W0,
            step_fn=lambda w: _softmax_step(*shards[w], lr=lr),
            sync_every=sync_every)
        acc_async = _accuracy(svc.anchor["fc"]["w"], Xte, yte)
        spread = svc.max_spread
    finally:
        svc.close()
    assert spread <= 3                       # s + 1
    assert res[0]["gate_blocks"] >= 0        # telemetry present
    assert acc_bsp > 0.9                     # the task was actually learned
    assert acc_async >= acc_bsp - 0.005, (acc_async, acc_bsp)


def test_worker_crash_does_not_deadlock_survivors():
    """Elasticity beyond the reference's fail-fast (comm_bus.hpp:22-24
    aborts the whole job): a worker that dies abruptly (no bye, no done)
    is detected by the service, excluded from the survivors' gates, and
    its already-applied clocks stay in the anchor. Without detection the
    survivor's s=1 gate would TimeoutError waiting on a dead peer."""
    import time as _time

    from poseidon_tpu.parallel.async_ssp import AsyncSSPClient
    params = _zeros_params((2, 2))
    svc = ParamService(params, n_workers=2)
    one = {"fc": {"w": np.ones((2, 2), np.float32)}}
    try:
        # the doomed worker pushes 2 clocks then crashes (sockets torn
        # down with no bye)
        doomed = AsyncSSPClient(1, ("127.0.0.1", svc.port), staleness=1,
                                n_workers=2)
        doomed.push(one)
        doomed.push(one)
        doomed._drain()
        doomed._stop.set()
        doomed._sender.join(timeout=5)
        doomed._push_sock.close()
        doomed._pull_sock.close()
        deadline = _time.time() + 10
        while 1 not in svc.failed_workers and _time.time() < deadline:
            _time.sleep(0.02)
        assert 1 in svc.failed_workers

        # the survivor runs 12 clocks at s=1 — far past the dead peer's
        # clock 1 — and must never block on it
        res = run_async_ssp_worker(
            0, 2, params, _counting_step(0), 12, staleness=1, service=svc)
        assert res["final_clock"] == 11
        # anchor = survivor's 12 + dead worker's 2 applied clocks
        np.testing.assert_allclose(svc.anchor["fc"]["w"],
                                   np.full((2, 2), 14.0))
        assert svc.done_workers == {0}
    finally:
        svc.close()


@pytest.mark.slow
@pytest.mark.skipif(not os.path.isdir(
    os.path.join(REPO, "examples/mnist/mnist_train_lmdb")),
    reason="synthetic MNIST LMDB not generated")
def test_cli_train_async_ssp_two_process(tmp_path):
    """The product surface: `train --async_ssp --staleness 2` across 2 REAL
    launcher processes training LeNet from the LMDB — independent jax
    runtimes, disjoint data shards, rank-0 parameter service, wait-free
    gates. Both ranks must exit clean, training must progress, and the
    tier telemetry (final clock + spread) must land in the rank-0 log."""
    scripts = os.path.join(REPO, "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import launch

    solver = tmp_path / "solver.prototxt"
    solver.write_text(f"""
net: "{REPO}/examples/mnist/lenet_train_test.prototxt"
base_lr: 0.01
lr_policy: "fixed"
momentum: 0.9
display: 5
max_iter: 12
test_interval: 0
snapshot_after_train: true
snapshot_prefix: "lenet_async"
random_seed: 7
""")
    (tmp_path / "p0").mkdir()
    (tmp_path / "p1").mkdir()
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    rc, raw_logs = launch.launch_local(
        2, 4, port,
        ["train", "--solver", str(solver), "--async_ssp",
         "--staleness", "2", "--steps_per_dispatch", "3",
         "--output_dir", str(tmp_path / "p{proc_id}")],
        capture=True)
    logs = [b.decode() for b in raw_logs]
    assert rc == 0, logs[0][-2000:] + logs[1][-2000:]
    assert "async-SSP tier: 2 members" in logs[0]
    assert "Iteration 10" in logs[0]
    # chunked dispatch (steps_per_dispatch=3): one flush clock per
    # dispatch, so the final clock is dispatch-count-1 (display/test
    # boundaries make the chunking pattern data-dependent — assert the
    # tier ran and flushed repeatedly, not an exact count)
    import re as _re
    m = _re.search(r"async_final_clock=(\d+)", logs[0])
    assert m and int(m.group(1)) >= 3, logs[0][-800:]
    # rank 0's post-train snapshot holds the final ANCHOR (all workers'
    # updates folded in), written through the standard snapshot path
    import numpy as np_
    snap = np_.load(str(tmp_path / "p0" / "lenet_async_iter_12.solverstate"
                                          ".npz"))
    assert any(k.startswith("params/") for k in snap.files)


@pytest.mark.slow
@pytest.mark.skipif(not os.path.isdir(
    os.path.join(REPO, "examples/mnist/mnist_train_lmdb")),
    reason="synthetic MNIST LMDB not generated")
def test_cli_async_ssp_composes_with_intra_process_strategies(tmp_path):
    """The full two-tier async deployment: each process runs a compiled
    4-device step with SFB on its FC layers (the per-step backward-time
    ICI exchange), while the wait-free service carries the cross-process
    tier — the reference's machine-internal-PS + inter-machine-Bösen
    split, with the inner tier compiled."""
    scripts = os.path.join(REPO, "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import launch

    solver = tmp_path / "solver.prototxt"
    solver.write_text(f"""
net: "{REPO}/examples/mnist/lenet_train_test.prototxt"
base_lr: 0.01
lr_policy: "fixed"
momentum: 0.9
display: 4
max_iter: 8
test_interval: 0
random_seed: 11
""")
    (tmp_path / "p0").mkdir()
    (tmp_path / "p1").mkdir()
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    rc, raw_logs = launch.launch_local(
        2, 4, port,
        ["train", "--solver", str(solver), "--async_ssp",
         "--staleness", "1", "--sfb-auto",
         "--output_dir", str(tmp_path / "p{proc_id}")],
        capture=True)
    logs = [b.decode() for b in raw_logs]
    assert rc == 0, logs[0][-2000:] + logs[1][-2000:]
    assert "async-SSP tier: 2 members" in logs[0]
    assert "Iteration 8" in logs[0] or "Iteration 4" in logs[0]


@pytest.mark.slow
def test_two_process_wait_free():
    """The deployment shape: 2 REAL processes through scripts/launch.py
    --local, rank 0 hosting the ParamService, rank 1 an artificial
    straggler (30 ms/clock), window wide open (s = 100). The fast rank
    must finish without one blocked gate while the straggler is mid-run —
    the wait-free execution the compiled SSP step's reconcile barrier
    cannot express — and the anchor must still learn the task."""
    scripts = os.path.join(REPO, "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import launch

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    rc, raw_logs = launch.launch_local(
        2, 1, port,
        ["--clocks", "40", "--staleness", "100",
         "--slow_rank", "1", "--slow_ms", "30"],
        capture=True,
        program=[sys.executable,
                 os.path.join(REPO, "examples/async_ssp/"
                                    "train_async_digits.py")])
    logs = [b.decode() for b in raw_logs]
    assert rc == 0, logs[0][-2000:] + logs[1][-2000:]
    lines = {}
    for log in logs:
        for ln in log.splitlines():
            if ln.startswith("{"):
                d = json.loads(ln)
                lines[d["rank"]] = d
    fast, slow = lines[0], lines[1]
    assert fast["gate_blocks"] == 0          # wait-free inside the window
    assert fast["blocked_s"] == 0.0
    assert fast["final_clock"] == 39 and slow["final_clock"] == 39
    # the straggler slept 40 x 30 ms; the fast rank must not have paid it
    assert fast["wall_s"] < 0.6 * slow["wall_s"], (fast, slow)
    assert fast["accuracy"] > 0.9, fast


def test_adarevision_matches_reference_formula():
    """server_logic='adarevision' on the ASYNC service must reproduce the
    reference server's rule exactly (adarevision_server_table_logic.cpp:
    52-175) — including the cross-boundary backlog the compiled tier
    cannot express: worker 1 pushes a gradient based on a PULL taken
    before worker 0's second push, so its g_bck covers exactly the
    updates applied since that snapshot. Verified against a float64
    NumPy replica driven through the same (push, pull) interleaving."""
    from poseidon_tpu.parallel.async_ssp import AsyncSSPClient
    eta0 = 0.05
    rs = np.random.RandomState(0)
    params = {"fc": {"w": rs.randn(3, 2).astype(np.float32)}}
    svc = ParamService(params, n_workers=2, server_logic="adarevision",
                       init_step=eta0)
    c0 = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=10,
                        n_workers=2)
    c1 = AsyncSSPClient(1, ("127.0.0.1", svc.port), staleness=10,
                        n_workers=2)
    u = [rs.randn(3, 2).astype(np.float32) for _ in range(4)]

    def push(cli, g):
        cli.push({"fc": {"w": g}})
        cli._drain()

    try:
        c1.refresh()                    # worker 1 bases at G = 0
        push(c0, u[0])                  # applied: u0
        push(c0, u[1])                  # applied: u0+u1
        push(c1, u[2])                  # based at 0 -> g_bck = u0+u1
        c0.refresh()                    # worker 0 re-bases at G = u0+u1+u2
        push(c0, u[3])                  # g_bck = 0 (nothing since its pull)
        got = np.asarray(svc.anchor["fc"]["w"], np.float64)
    finally:
        c0.close()
        c1.close()
        svc.close()

    # float64 replica of the exact server rule
    av = np.asarray(params["fc"]["w"], np.float64)
    z = np.ones_like(av)
    zmax = np.ones_like(av)
    G = np.zeros_like(av)
    base = {0: np.zeros_like(av), 1: np.zeros_like(av)}
    order = [(0, u[0]), (0, u[1]), (1, u[2])]
    for w, ug in order:
        ug = np.asarray(ug, np.float64)
        g_bck = G - base[w]
        eta_old = eta0 / np.sqrt(zmax)
        z = z + ug * (ug + 2.0 * g_bck)
        zmax = np.maximum(zmax, z)
        eta = eta0 / np.sqrt(zmax)
        av = av - eta * ug + (eta_old - eta) * g_bck
        G = G + ug
    base[0] = G.copy()                  # c0.refresh()
    ug = np.asarray(u[3], np.float64)
    g_bck = G - base[0]
    eta_old = eta0 / np.sqrt(zmax)
    z = z + ug * (ug + 2.0 * g_bck)
    zmax = np.maximum(zmax, z)
    eta = eta0 / np.sqrt(zmax)
    av = av - eta * ug + (eta_old - eta) * g_bck
    np.testing.assert_allclose(got, av, rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_adarevision_digits_converges():
    """AdaRevision on the async tier end to end: 2 workers (one straggler)
    pushing raw gradients, the server owning the delay-corrected lr —
    digits accuracy must reach the same ballpark as the additive tier."""
    (Xtr, ytr), (Xte, yte) = _digits()
    half = len(Xtr) // 2
    shards = [(Xtr[:half], ytr[:half]), (Xtr[half:], ytr[half:])]

    def grad_step(w):
        X, y = shards[w]
        n = len(X)

        def step(params, it):
            rs = np.random.RandomState(it)
            sel = rs.randint(0, n, size=128)
            xb, yb = X[sel], y[sel]
            W = params["fc"]["w"]
            logits = xb @ W
            logits -= logits.max(axis=1, keepdims=True)
            p = np.exp(logits)
            p /= p.sum(axis=1, keepdims=True)
            loss = -np.log(p[np.arange(128), yb] + 1e-9).mean()
            p[np.arange(128), yb] -= 1.0
            return {"fc": {"w": xb.T @ p / 128}}, loss
        return step

    W0 = {"fc": {"w": np.zeros((64, 10), np.float32)}}
    svc = ParamService(W0, n_workers=2, server_logic="adarevision",
                       init_step=0.3)
    try:
        _run_workers(2, staleness=2, n_clocks=150, slow_map={1: 0.002},
                     service=svc, params=W0, step_fn=grad_step,
                     sync_every=4, server_logic="adarevision",
                     init_step=0.3)
        acc = _accuracy(svc.anchor["fc"]["w"], Xte, yte)
        spread = svc.max_spread
    finally:
        svc.close()
    assert spread <= 3
    assert acc > 0.92, acc


# --------------------------------------------------------------------------- #
# connection authentication (round 6: pickle frames need a gate)
# --------------------------------------------------------------------------- #

def test_auth_rejects_bad_token_before_any_frame():
    """A connection with the wrong shared secret is closed at the
    handshake: no pickle frame from it is ever parsed (the service's
    frame counters stay untouched), and auth_failures records it."""
    import struct

    params = _zeros_params()
    svc = ParamService(params, n_workers=1, auth_token="s3cret")
    try:
        sk = socket.create_connection(("127.0.0.1", svc.port), timeout=5.0)
        sk.settimeout(5.0)
        # read the challenge, answer garbage of the right length
        from poseidon_tpu.proto.wire import AUTH_MAGIC, AUTH_NONCE_LEN
        head = sk.recv(len(AUTH_MAGIC) + AUTH_NONCE_LEN)
        assert head.startswith(AUTH_MAGIC)
        sk.sendall(b"\x00" * (32 + AUTH_NONCE_LEN))  # bad digest + nonce
        # server must close without ever reading a frame; a subsequent
        # huge "frame" we send goes nowhere
        try:
            sk.sendall(struct.pack("!Q", 1 << 40) + b"boom")
        except OSError:
            pass
        try:
            assert sk.recv(1) == b""  # service closed our connection
        except ConnectionError:
            pass  # RST instead of FIN — equally closed
        sk.close()
        deadline = __import__("time").time() + 5.0
        while svc.auth_failures == 0 and __import__("time").time() < deadline:
            __import__("time").sleep(0.01)
        assert svc.auth_failures == 1
        assert svc.bad_frames == 0       # nothing ever reached the parser
        assert svc.clocks == {0: -1}     # and no state changed
    finally:
        svc.close()


def test_auth_good_token_trains_end_to_end():
    """With matching tokens on both sides the full worker protocol runs
    unchanged (handshake is transparent to the frame layer)."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=2, auth_token="tok123")
    try:
        _run_workers(2, 10, 3, {}, svc, params,
                     client_opts={"auth_token": "tok123"})
        np.testing.assert_allclose(svc.anchor["fc"]["w"], 6.0)
    finally:
        svc.close()


def test_auth_wrong_client_token_fails_rendezvous():
    """A client dialing with the WRONG token never gets a connection: the
    rendezvous deadline surfaces the failure instead of silently feeding
    frames to a service that drops them."""
    from poseidon_tpu.parallel.async_ssp import AsyncSSPClient

    params = _zeros_params()
    svc = ParamService(params, n_workers=1, auth_token="right")
    try:
        with pytest.raises((OSError, EOFError, ConnectionError)):
            AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=0,
                           n_workers=1, retry_s=0.8, auth_token="wrong")
        assert svc.auth_failures >= 1
    finally:
        svc.close()


def test_auth_token_from_launcher_env(monkeypatch):
    """The launcher distributes the secret via POSEIDON_ASYNC_TOKEN; both
    sides pick it up with no explicit plumbing."""
    monkeypatch.setenv("POSEIDON_ASYNC_TOKEN", "envtok")
    params = _zeros_params()
    svc = ParamService(params, n_workers=1)
    assert svc.auth_token == "envtok"
    try:
        _run_workers(1, 5, 2, {}, svc, params)
        np.testing.assert_allclose(svc.anchor["fc"]["w"], 2.0)
    finally:
        svc.close()


def test_default_bind_is_loopback():
    """Unless a host is explicitly passed, the service listens on
    127.0.0.1 only — pickle frames are never reachable from off-host."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=1)
    try:
        assert svc._srv.getsockname()[0] == "127.0.0.1"
    finally:
        svc.close()


def test_auth_client_rejects_spoofed_service():
    """Mutual handshake: a spoofed endpoint that replays the challenge
    magic but cannot prove the token must be rejected by the CLIENT before
    it parses a single frame (pickle loaders on workers are as dangerous
    as on the service)."""
    import threading as _threading

    from poseidon_tpu.proto.wire import (AUTH_DIGEST_LEN, AUTH_MAGIC,
                                         AUTH_NONCE_LEN, AuthError,
                                         client_handshake)

    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def spoof():
        conn, _ = srv.accept()
        conn.sendall(AUTH_MAGIC + b"\x11" * AUTH_NONCE_LEN)
        try:
            conn.recv(AUTH_DIGEST_LEN + AUTH_NONCE_LEN)
            conn.sendall(b"\x00" * AUTH_DIGEST_LEN)  # cannot prove token
        except OSError:
            pass

    t = _threading.Thread(target=spoof, daemon=True)
    t.start()
    sk = socket.create_connection(("127.0.0.1", port), timeout=5.0)
    try:
        with pytest.raises(AuthError, match="prove"):
            client_handshake(sk, "the-real-token")
    finally:
        sk.close()
        srv.close()
        t.join(timeout=5)
