"""Serving-tier suite (ISSUE 2): bucketed AOT executor parity, dynamic
micro-batcher triggers, backpressure, checkpoint hot-reload, fault-proxy
chaos, and graceful shutdown.

Everything here is CPU-safe and binds port 0 on loopback only — no fixed
ports, no flakes — so the whole file runs under the tier-1 command. The
chaos tests reuse runtime/faults.py's deterministic proxy (exact byte
counts and connection indices, nothing random).
"""

import os
import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.serving

DEPLOY_NET = """
name: "servnet"
input: "data"
input_dim: 1 input_dim: 3 input_dim: 8 input_dim: 8
layers { name: "conv" type: CONVOLUTION bottom: "data" top: "conv"
  convolution_param { num_output: 4 kernel_size: 3
    weight_filler { type: "xavier" } } }
layers { name: "fc" type: INNER_PRODUCT bottom: "conv" top: "fc"
  inner_product_param { num_output: 3 weight_filler { type: "xavier" } } }
layers { name: "prob" type: SOFTMAX bottom: "fc" top: "prob" }
"""


def _build_executor(buckets=(1, 2, 4)):
    import jax
    from poseidon_tpu.core.net import Net
    from poseidon_tpu.proto.messages import load_net_from_string
    from poseidon_tpu.serving.executor import BucketedExecutor

    net = Net(load_net_from_string(DEPLOY_NET), "TEST")
    params = net.init(jax.random.PRNGKey(7))
    return BucketedExecutor(net, params, buckets=buckets)


def _rows(n, seed=0):
    return np.random.RandomState(seed).randn(n, 3, 8, 8).astype(np.float32)


# --------------------------------------------------------------------------- #
# executor: bucketed AOT cache
# --------------------------------------------------------------------------- #

def test_bucketed_executor_matches_direct_jit():
    """Padding to a bucket and slicing back is BIT-IDENTICAL to a direct
    jit forward at the request's own shape (row independence)."""
    import jax

    ex = _build_executor()
    direct = jax.jit(lambda p, i: ex.net.apply(p, i, train=False).outputs)
    for n in (1, 2, 3, 4):
        x = _rows(n, seed=n)
        got = ex.infer({"data": x})["prob"]
        want = np.asarray(direct(ex._params, {"data": x})["prob"])
        assert got.shape == (n, 3)
        np.testing.assert_array_equal(got, want)


def test_bucket_selection_padding_and_limits():
    ex = _build_executor()
    assert [ex.bucket_for(n) for n in (1, 2, 3, 4)] == [1, 2, 4, 4]
    ex.infer({"data": _rows(3)})
    assert ex.calls[4] == 1 and ex.rows_padded == 1 and ex.rows_served == 3
    with pytest.raises(ValueError, match="exceeds the largest bucket"):
        ex.infer({"data": _rows(5)})
    with pytest.raises(ValueError, match="row shape"):
        ex.infer({"data": np.zeros((1, 3, 4, 4), np.float32)})


def test_executor_warm_precompiles_no_request_trace():
    """Every bucket's executable exists before the first request."""
    ex = _build_executor(buckets=(1, 2))
    assert sorted(ex._compiled) == [1, 2]


def test_swap_params_validates_and_applies():
    import jax

    ex = _build_executor(buckets=(2,))
    x = _rows(2)
    before = ex.infer({"data": x})["prob"]
    doubled = jax.tree_util.tree_map(lambda v: v * 2.0, ex._params)
    assert ex.swap_params(doubled) == 1
    after = ex.infer({"data": x})["prob"]
    assert not np.allclose(before, after)
    # wrong tree shape is refused (the executables are shape-keyed)
    bad = {"conv": {"w": np.zeros((1, 1), np.float32)}}
    with pytest.raises(ValueError):
        ex.swap_params(bad)
    assert ex.params_version == 1


# --------------------------------------------------------------------------- #
# batcher: flush triggers, backpressure, deadlines
# --------------------------------------------------------------------------- #

class FakeExecutor:
    """Duck-typed executor: records flushed batch sizes, optional per-call
    stall (to hold the flush thread busy deterministically)."""

    def __init__(self, max_batch=4, delay_s=0.0):
        self.input_names = ["x"]
        self.max_batch = max_batch
        self.delay_s = delay_s
        self.batch_rows = []
        self.calls = {max_batch: 0}
        self.rows_served = 0
        self.rows_padded = 0
        self.params_version = 0

    def infer(self, inputs):
        if self.delay_s:
            time.sleep(self.delay_s)
        rows = int(np.shape(inputs["x"])[0])
        self.batch_rows.append(rows)
        self.rows_served += rows
        self.calls[self.max_batch] += 1
        return {"y": np.asarray(inputs["x"], np.float32) * 2.0}


def test_batcher_flushes_on_size_trigger():
    """A full batch dispatches immediately — max_delay_s is huge, so only
    the SIZE trigger can explain a fast flush."""
    from poseidon_tpu.serving.batcher import DynamicBatcher

    ex = FakeExecutor(max_batch=4)
    b = DynamicBatcher(ex, max_delay_s=30.0, max_queue=16)
    try:
        results = [None] * 4
        ts = []
        for i in range(4):
            t = threading.Thread(
                target=lambda i=i: results.__setitem__(
                    i, b.submit({"x": np.full((1, 2), i, np.float32)})),
                daemon=True)
            ts.append(t)
        t0 = time.monotonic()
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=10.0)
        assert time.monotonic() - t0 < 5.0, "size trigger did not fire"
        assert all(r is not None for r in results)
        # each caller got ITS rows back (fan-out slicing)
        for i, r in enumerate(results):
            np.testing.assert_array_equal(r["y"],
                                          np.full((1, 2), 2.0 * i))
        assert ex.batch_rows and max(ex.batch_rows) == 4
    finally:
        b.close()


def test_batcher_flushes_on_deadline_trigger():
    """A lone request never waits past max_delay_s for company."""
    from poseidon_tpu.serving.batcher import DynamicBatcher

    ex = FakeExecutor(max_batch=64)
    b = DynamicBatcher(ex, max_delay_s=0.05, max_queue=16)
    try:
        t0 = time.monotonic()
        out = b.submit({"x": np.ones((1, 2), np.float32)})
        waited = time.monotonic() - t0
        assert out["y"].shape == (1, 2)
        assert waited < 2.0
        assert ex.batch_rows == [1]          # partial batch: deadline fired
        assert b.fill_ratio() < 1.0
    finally:
        b.close()


def test_full_queue_sheds_explicitly_not_a_hang():
    """Admission control: with the flush thread held busy and the queue at
    max_queue, the next submit raises ShedError IMMEDIATELY."""
    from poseidon_tpu.serving.batcher import DynamicBatcher, ShedError

    ex = FakeExecutor(max_batch=1, delay_s=0.6)
    b = DynamicBatcher(ex, max_delay_s=0.0, max_queue=2)
    try:
        threads = []
        for i in range(3):  # 1 in-flight (popped) + 2 queued
            t = threading.Thread(
                target=lambda: b.submit({"x": np.ones((1, 1), np.float32)},
                                        timeout_s=30.0),
                daemon=True)
            t.start()
            threads.append(t)
            time.sleep(0.08)   # let the flush thread pop the first one
        t0 = time.monotonic()
        with pytest.raises(ShedError, match="queue full"):
            b.submit({"x": np.ones((1, 1), np.float32)})
        assert time.monotonic() - t0 < 0.5, "shed must be immediate"
        assert b.shed_count == 1
        for t in threads:
            t.join(timeout=10.0)
    finally:
        b.close()


def test_request_deadline_expires_in_queue():
    from poseidon_tpu.serving.batcher import DeadlineError, DynamicBatcher

    ex = FakeExecutor(max_batch=1, delay_s=0.3)
    b = DynamicBatcher(ex, max_delay_s=0.0, max_queue=8)
    try:
        # occupy the flush thread, then submit with a deadline shorter than
        # the stall: by dispatch time it has expired
        blocker = threading.Thread(
            target=lambda: b.submit({"x": np.ones((1, 1), np.float32)}),
            daemon=True)
        blocker.start()
        time.sleep(0.05)
        with pytest.raises(DeadlineError):
            b.submit({"x": np.ones((1, 1), np.float32)}, deadline_s=0.01)
        assert b.deadline_expired == 1
        blocker.join(timeout=10.0)
    finally:
        b.close()


def test_oversized_request_rejected():
    from poseidon_tpu.serving.batcher import DynamicBatcher

    ex = FakeExecutor(max_batch=2)
    b = DynamicBatcher(ex, max_delay_s=0.0)
    try:
        with pytest.raises(ValueError, match="split it client-side"):
            b.submit({"x": np.ones((3, 1), np.float32)})
    finally:
        b.close()


# --------------------------------------------------------------------------- #
# server + client: roundtrip, stats op, containment
# --------------------------------------------------------------------------- #

def _serve(executor=None, **kw):
    from poseidon_tpu.serving.server import InferenceServer

    return InferenceServer(executor or _build_executor(),
                           max_delay_s=kw.pop("max_delay_s", 0.002), **kw)


def test_server_roundtrip_and_stats_op():
    from poseidon_tpu.serving.client import ServingClient

    srv = _serve()
    cli = ServingClient(srv.addr)
    try:
        x = _rows(2)
        out = cli.infer({"data": x})
        np.testing.assert_array_equal(
            out["prob"], srv.executor.infer({"data": x})["prob"])
        st = cli.stats()
        for key in ("latency", "queue_depth", "batch_fill", "shed",
                    "bucket_calls", "reloads", "params_version"):
            assert key in st, f"stats op missing {key}"
        assert st["latency"]["count"] >= 1
        assert st["shed"] == 0
    finally:
        cli.close()
        srv.shutdown()


def test_malformed_frame_drops_one_connection_not_the_server():
    """ParamService containment pattern: garbage from one peer kills ITS
    connection; the next client is served normally."""
    import socket as _socket

    from poseidon_tpu.serving.client import ServingClient

    srv = _serve()
    try:
        sk = _socket.create_connection(srv.addr)
        sk.sendall(b"GET / HTTP/1.1\r\nHost: nope\r\n\r\n")  # not a frame
        # server must close THIS connection (bad header -> oversized length
        # -> FrameError); clean FIN or RST both count as dropped
        sk.settimeout(5.0)
        try:
            assert sk.recv(1) == b""
        except ConnectionError:
            pass
        sk.close()
        cli = ServingClient(srv.addr)
        try:
            out = cli.infer({"data": _rows(1)})
            assert out["prob"].shape == (1, 3)
        finally:
            cli.close()
        assert srv.bad_frames >= 1
    finally:
        srv.shutdown()


def test_absurd_length_prefix_rejected_before_allocation(monkeypatch):
    """PROTO207's fix (proto/wire.py): a frame header claiming an absurd
    length is refused as a FrameError BEFORE any payload allocation —
    the configurable cap (POSEIDON_MAX_FRAME_BYTES /
    set_max_frame_bytes), not a multi-gigabyte recv buffer, decides.
    The offending connection dies; the server keeps serving."""
    import socket as _socket
    import struct as _struct

    from poseidon_tpu.proto import wire
    from poseidon_tpu.serving.client import ServingClient

    # pin the ambient environment: an operator legitimately exporting
    # the knob must not change what this test asserts about defaults
    monkeypatch.delenv(wire.MAX_FRAME_ENV, raising=False)

    # unit level: the cap knob resolves override > env > default and the
    # recv path refuses an over-cap header without reading the payload
    assert wire.max_frame_bytes() == wire.DEFAULT_MAX_FRAME
    wire.set_max_frame_bytes(1024)
    try:
        assert wire.max_frame_bytes() == 1024
        with pytest.raises(ValueError):
            wire.set_max_frame_bytes(0)
    finally:
        wire.set_max_frame_bytes(None)
    monkeypatch.setenv(wire.MAX_FRAME_ENV, "4096")
    assert wire.max_frame_bytes() == 4096
    monkeypatch.delenv(wire.MAX_FRAME_ENV)

    srv = _serve()
    try:
        sk = _socket.create_connection(srv.addr)
        # a "legitimate"-looking header claiming a 2**62-byte frame: the
        # server must drop the connection at the cap check (loudly, as a
        # bad frame), never attempt the recv
        sk.sendall(_struct.pack("!Q", 1 << 62))
        sk.settimeout(5.0)
        try:
            assert sk.recv(1) == b""
        except ConnectionError:
            pass
        sk.close()
        # send-side refusal names the knob instead of wedging the peer —
        # and is deliberately NOT a ConnectionError/FrameError, so the
        # reconnect-and-replay machinery can never retry a deterministic
        # over-cap frame for the whole backoff deadline
        class _FakeSock:
            def sendall(self, data):
                raise AssertionError("oversized frame reached the socket")
        wire.set_max_frame_bytes(64)
        try:
            with pytest.raises(wire.FrameTooLargeError,
                               match="POSEIDON_MAX_FRAME"):
                wire.send_frame(_FakeSock(), b"x" * 1024)
            assert not issubclass(wire.FrameTooLargeError, ConnectionError)
        finally:
            wire.set_max_frame_bytes(None)
        # an unusable env value warns instead of silently reverting
        monkeypatch.setenv(wire.MAX_FRAME_ENV, "2GB")
        with pytest.warns(RuntimeWarning, match="not a positive integer"):
            assert wire.max_frame_bytes() == wire.DEFAULT_MAX_FRAME
        monkeypatch.delenv(wire.MAX_FRAME_ENV)
        # the server survived and still serves
        cli = ServingClient(srv.addr)
        try:
            out = cli.infer({"data": _rows(1)})
            assert out["prob"].shape == (1, 3)
        finally:
            cli.close()
        assert srv.bad_frames >= 1
    finally:
        srv.shutdown()


def test_unknown_kind_gets_error_reply():
    from poseidon_tpu.proto.wire import recv_frame, send_frame
    import socket as _socket

    srv = _serve()
    try:
        sk = _socket.create_connection(srv.addr)
        send_frame(sk, {"kind": "no-such-op"})
        reply = recv_frame(sk)
        assert reply["ok"] is False and "no-such-op" in reply["error"]
        # connection survives a bad REQUEST (only torn frames drop it)
        send_frame(sk, {"kind": "health"})
        assert recv_frame(sk)["ok"] is True
        sk.close()
    finally:
        srv.shutdown()


# --------------------------------------------------------------------------- #
# checkpoint hot-reload
# --------------------------------------------------------------------------- #

def _snapshot_params(prefix, net, params, it):
    """Write a real <prefix>_iter_<it>.solverstate.npz + .caffemodel pair
    through the training tier's own snapshot writer."""
    import jax.numpy as jnp
    from poseidon_tpu.parallel.trainer import init_train_state
    from poseidon_tpu.runtime.checkpoint import snapshot

    state = init_train_state(params)
    state = state._replace(solver=state.solver._replace(
        it=jnp.asarray(it, jnp.int32)))
    return snapshot(prefix, net, params, state)


def test_hot_reload_swaps_params_mid_stream(tmp_path):
    """A newer snapshot swaps in atomically: concurrent in-flight requests
    NEVER error, and results flip from old-params to new-params output."""
    import jax

    from poseidon_tpu.serving.client import ServingClient
    from poseidon_tpu.serving.reloader import CheckpointReloader

    ex = _build_executor(buckets=(1, 2, 4))
    prefix = str(tmp_path / "snap" / "servnet")
    _snapshot_params(prefix, ex.net, ex._params, it=1)
    reloader = CheckpointReloader(ex, prefix, start=False)
    assert reloader.check_now() is True      # picks up iter 1 immediately
    assert reloader.reloads == 1

    srv = _serve(ex, reloader=reloader)
    x = _rows(2)
    before = None
    errors = []
    stop = threading.Event()

    def hammer():
        from poseidon_tpu.serving.client import ServingClient as C
        c = C(srv.addr)
        try:
            while not stop.is_set():
                try:
                    c.infer({"data": x})
                except Exception as e:  # noqa: BLE001 — the assertion
                    errors.append(e)
                    return
        finally:
            c.close()

    cli = ServingClient(srv.addr)
    try:
        before = cli.infer({"data": x})["prob"]
        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        doubled = jax.tree_util.tree_map(lambda v: v * 2.0, ex._params)
        _snapshot_params(prefix, ex.net, doubled, it=2)
        reply = cli.reload()
        assert reply["ok"] and reply["reloaded"] is True
        after = cli.infer({"data": x})["prob"]
        time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        assert not errors, f"in-flight request errored during reload: " \
                           f"{errors[0]}"
        assert not np.allclose(before, after)
        assert ex.params_version == 2        # initial pickup + hot reload
    finally:
        stop.set()
        cli.close()
        srv.shutdown()


def test_reloader_ignores_tmp_litter_and_survives_torn_snapshot(tmp_path):
    """tmp litter is invisible to discovery; a corrupt newest snapshot is
    counted and skipped — the server keeps serving the previous params."""
    from poseidon_tpu.serving.reloader import CheckpointReloader

    ex = _build_executor(buckets=(1,))
    prefix = str(tmp_path / "snap" / "servnet")
    _snapshot_params(prefix, ex.net, ex._params, it=1)
    rel = CheckpointReloader(ex, prefix, start=False)
    assert rel.check_now() is True
    # a writer killed mid-snapshot leaves tmp litter: never a reload
    litter = tmp_path / "snap" / "servnet_iter_9.solverstate.npz.tmp.12345"
    litter.write_bytes(b"\x00" * 64)
    assert rel.check_now() is False
    # a torn "complete-looking" file: load fails, old params keep serving
    torn = tmp_path / "snap" / "servnet_iter_10.solverstate.npz"
    torn.write_bytes(b"not-an-npz")
    ver_before = ex.params_version
    assert rel.check_now() is False
    assert rel.failed_reloads == 1 and rel.last_error
    assert ex.params_version == ver_before
    out = ex.infer({"data": _rows(1)})
    assert out["prob"].shape == (1, 3)


def test_reloader_seeded_with_serving_snapshot_never_reswaps(tmp_path):
    """Seeding current_path with the snapshot --weights already loaded
    means the first poll is a no-op (no redundant re-load, no backwards
    swap); only a strictly newer snapshot triggers a reload."""
    import jax

    from poseidon_tpu.serving.reloader import CheckpointReloader

    ex = _build_executor(buckets=(1,))
    prefix = str(tmp_path / "snap" / "servnet")
    _, state_path = _snapshot_params(prefix, ex.net, ex._params, it=5)
    rel = CheckpointReloader(ex, prefix, start=False,
                             current_path=state_path)
    assert rel.check_now() is False and rel.reloads == 0
    # an OLDER snapshot appearing later must not regress the serving params
    _snapshot_params(prefix, ex.net, ex._params, it=3)
    assert rel.check_now() is False
    # a strictly newer one swaps
    _snapshot_params(prefix, ex.net,
                     jax.tree_util.tree_map(lambda v: v * 2.0, ex._params),
                     it=9)
    assert rel.check_now() is True and rel.reloads == 1


def test_reloader_background_thread_polls(tmp_path):
    import jax

    from poseidon_tpu.serving.reloader import CheckpointReloader

    ex = _build_executor(buckets=(1,))
    prefix = str(tmp_path / "snap" / "servnet")
    rel = CheckpointReloader(ex, prefix, poll_s=0.05)
    try:
        _snapshot_params(prefix, ex.net,
                         jax.tree_util.tree_map(lambda v: v * 3.0,
                                                ex._params), it=1)
        deadline = time.time() + 10.0
        while rel.reloads < 1:
            assert time.time() < deadline, "watcher never picked up snapshot"
            time.sleep(0.02)
    finally:
        rel.close()


# --------------------------------------------------------------------------- #
# chaos: runtime/faults.py proxy between client and server
# --------------------------------------------------------------------------- #

def test_server_survives_fault_proxy_chaos():
    """drop + truncate + sever rules between client and server: the client
    retries through every cut via retry_with_backoff; the server contains
    the torn frames and keeps serving."""
    from poseidon_tpu.runtime.faults import FaultProxy, FaultRule
    from poseidon_tpu.serving.client import ServingClient

    srv = _serve()
    proxy = FaultProxy(srv.addr)
    # conn 0: accepted then closed (dead LB slot) — exercises redial
    proxy.add_rule(FaultRule(action="drop", conn=0))
    # conn 1: congested hop — slow must NOT read as dead (no reconnect)
    proxy.add_rule(FaultRule(action="delay", conn=1, delay_s=0.05))
    # conn 2: cut after 40 bytes of request — a torn frame mid-request
    proxy.add_rule(FaultRule(action="truncate", conn=2, after_bytes=40))
    try:
        cli = ServingClient(proxy.addr, retry_deadline_s=10.0,
                            backoff_base_s=0.01, backoff_cap_s=0.05)
        x = _rows(1)
        want = srv.executor.infer({"data": x})["prob"]
        out1 = cli.infer({"data": x})          # conn0 dropped -> conn1 works
        np.testing.assert_array_equal(out1["prob"], want)
        reconnects_after_delay = cli.reconnects
        out_slow = cli.infer({"data": x})      # still conn1, delayed chunks
        np.testing.assert_array_equal(out_slow["prob"], want)
        assert cli.reconnects == reconnects_after_delay, \
            "a delayed (slow-but-alive) channel must not trigger reconnect"
        proxy.sever_all()                      # hard partition mid-run
        out2 = cli.infer({"data": x})          # conn2 truncated -> conn3
        np.testing.assert_array_equal(out2["prob"], want)
        assert proxy.accepted >= 4
        assert srv.bad_frames >= 1             # the torn frame was contained
        cli.close()
        # the server itself never wedged: a direct client still works
        direct = ServingClient(srv.addr)
        assert direct.infer({"data": x})["prob"].shape == (1, 3)
        direct.close()
    finally:
        proxy.close()
        srv.shutdown()


def test_kill_mid_request_client_reconnects_and_completes():
    """The acceptance scenario: the connection dies at an exact byte count
    MID-REQUEST; the client redials via retry_with_backoff, resends, and
    completes — the caller never sees the cut."""
    from poseidon_tpu.runtime.faults import FaultProxy, FaultRule
    from poseidon_tpu.serving.client import ServingClient

    srv = _serve()
    proxy = FaultProxy(srv.addr)
    # the FIRST connection is cut 40 bytes into the request frame — past the
    # wire-codec negotiation frame the client sends during _dial (header +
    # pickled offer), so the cut tears the request itself, not the dial
    import pickle
    from poseidon_tpu.proto.wire import WIRE_CODEC_VERSION
    neg = pickle.dumps({"kind": "wire", "codec": WIRE_CODEC_VERSION},
                       protocol=pickle.HIGHEST_PROTOCOL)
    proxy.add_rule(FaultRule(action="sever", conn=0,
                             after_bytes=len(neg) + 8 + 40))
    try:
        cli = ServingClient(proxy.addr, retry_deadline_s=10.0,
                            backoff_base_s=0.01, backoff_cap_s=0.05)
        x = _rows(2, seed=3)
        out = cli.infer({"data": x})
        np.testing.assert_array_equal(
            out["prob"], srv.executor.infer({"data": x})["prob"])
        assert cli.reconnects >= 1
        cli.close()
    finally:
        proxy.close()
        srv.shutdown()


def test_shed_response_is_explicit_over_the_wire():
    """Backpressure reaches the CLIENT as a structured shed reply, not a
    stall: hold the flush thread busy, fill the queue, assert the next
    request's refusal arrives fast and flagged."""
    from poseidon_tpu.serving.client import ServingClient, ServingError
    from poseidon_tpu.serving.server import InferenceServer

    ex = FakeExecutor(max_batch=1, delay_s=0.6)
    srv = InferenceServer(ex, max_delay_s=0.0, max_queue=2)
    try:
        hammers = []
        for _ in range(3):
            c = ServingClient(srv.addr)
            t = threading.Thread(
                target=lambda c=c: c.infer({"x": np.ones((1, 1),
                                                         np.float32)}),
                daemon=True)
            t.start()
            hammers.append((c, t))
            time.sleep(0.08)
        cli = ServingClient(srv.addr)
        t0 = time.monotonic()
        with pytest.raises(ServingError) as ei:
            cli.infer({"x": np.ones((1, 1), np.float32)})
        assert ei.value.shed is True
        assert time.monotonic() - t0 < 0.5
        st = cli.stats()
        assert st["shed"] >= 1
        cli.close()
        for c, t in hammers:
            t.join(timeout=10.0)
            c.close()
    finally:
        srv.shutdown()


def test_malformed_request_rejected_at_admission_not_cobatched():
    """A wrong-shaped request is refused with ITS error at submit time;
    a concurrent valid request in the same flush window is unaffected."""
    from poseidon_tpu.serving.client import ServingClient, ServingError

    srv = _serve(max_delay_s=0.05)        # window wide enough to co-batch
    good_cli = ServingClient(srv.addr)
    bad_cli = ServingClient(srv.addr)
    try:
        results = {}

        def good():
            results["good"] = good_cli.infer({"data": _rows(2)})

        t = threading.Thread(target=good, daemon=True)
        t.start()
        with pytest.raises(ServingError, match="row shape"):
            bad_cli.infer({"data": np.zeros((1, 3, 4, 4), np.float32)})
        t.join(timeout=10.0)
        assert results["good"]["prob"].shape == (2, 3)
    finally:
        good_cli.close()
        bad_cli.close()
        srv.shutdown()


def test_executor_failure_is_server_error_not_bad_frame():
    """A server-side executor crash reaches the client as server_error —
    never billed to the client's frame hygiene."""
    from poseidon_tpu.serving.client import ServingClient, ServingError
    from poseidon_tpu.serving.server import InferenceServer

    class ExplodingExecutor(FakeExecutor):
        def infer(self, inputs):
            raise RuntimeError("XLA device exploded")

    srv = InferenceServer(ExplodingExecutor(max_batch=2), max_delay_s=0.0)
    cli = ServingClient(srv.addr)
    try:
        with pytest.raises(ServingError, match="exploded") as ei:
            cli.infer({"x": np.ones((1, 1), np.float32)})
        assert not ei.value.shed and not ei.value.deadline_exceeded
        assert srv.server_errors == 1
        assert srv.bad_frames == 0
    finally:
        cli.close()
        srv.shutdown()


# --------------------------------------------------------------------------- #
# graceful shutdown
# --------------------------------------------------------------------------- #

def test_graceful_shutdown_drains_no_request_silently_dropped():
    """Every request admitted before the stop gets a REPLY (result, not a
    dropped socket); new connections are refused afterwards."""
    import socket as _socket

    from poseidon_tpu.serving.client import ServingClient
    from poseidon_tpu.serving.server import InferenceServer

    ex = FakeExecutor(max_batch=1, delay_s=0.15)
    srv = InferenceServer(ex, max_delay_s=0.0, max_queue=32)
    results, errors = [], []

    def one_request():
        c = ServingClient(srv.addr, retry_deadline_s=1.0)
        try:
            results.append(c.infer({"x": np.ones((1, 1), np.float32)}))
        except Exception as e:  # noqa: BLE001 — the assertion
            errors.append(e)
        finally:
            c.close()

    threads = [threading.Thread(target=one_request, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.1)            # requests admitted / in flight
    srv.request_stop()
    srv.shutdown(drain=True)
    for t in threads:
        t.join(timeout=15.0)
    assert not errors, f"request dropped during drain: {errors[0]}"
    assert len(results) == 4
    assert all(r["y"].shape == (1, 1) for r in results)
    # listener is closed: a fresh connection is refused
    with pytest.raises(OSError):
        _socket.create_connection(srv.addr, timeout=0.5)


def test_submissions_after_stop_get_shed_reply():
    from poseidon_tpu.serving.batcher import DynamicBatcher, ShedError

    ex = FakeExecutor(max_batch=2)
    b = DynamicBatcher(ex, max_delay_s=0.0)
    b.close(drain=True)
    with pytest.raises(ShedError, match="shutting down"):
        b.submit({"x": np.ones((1, 1), np.float32)})


def test_serve_cli_sigterm_exits_zero(tmp_path):
    """`python -m poseidon_tpu serve` handles SIGTERM by draining and
    exiting 0 with a final stats line (the ops contract)."""
    import signal
    import subprocess
    import sys

    model = tmp_path / "deploy.prototxt"
    model.write_text(DEPLOY_NET)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "poseidon_tpu", "serve",
         "--model", str(model), "--buckets", "1,2", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=os.path.dirname(os.path.dirname(__file__)))
    addr = None
    try:
        deadline = time.time() + 120.0
        for line in proc.stdout:
            if "listening on" in line:
                host, port = line.rsplit(" ", 1)[-1].strip().split(":")
                addr = (host, int(port))
                break
            assert time.time() < deadline, "server never came up"
        assert addr is not None
        from poseidon_tpu.serving.client import ServingClient
        cli = ServingClient(addr, connect_deadline_s=10.0)
        out = cli.infer({"data": _rows(1)})
        assert out["prob"].shape == (1, 3)
        cli.close()
        proc.send_signal(signal.SIGTERM)
        rest = proc.stdout.read()
        rc = proc.wait(timeout=30.0)
        assert rc == 0, f"serve exited {rc}: {rest[-2000:]}"
        assert "serving_final_stats" in rest
    finally:
        if proc.poll() is None:
            proc.kill()


# --------------------------------------------------------------------------- #
# CLI + bench plumbing
# --------------------------------------------------------------------------- #

def test_cli_serve_parser_defaults():
    from poseidon_tpu.runtime.cli import build_parser

    args = build_parser().parse_args(["serve", "--model", "m.prototxt"])
    # unset --buckets is a TunedPlan sentinel; resolution falls back to
    # the built-in ladder when no plan is persisted for the deploy net
    assert args.buckets == "" and args.port == 0
    from poseidon_tpu.runtime.cli import _resolve_serve_buckets
    args.model = ""          # no deploy net -> no plan lookup
    assert _resolve_serve_buckets(args) == "1,4,16,64"
    args.buckets = "1,8"     # explicit flag always wins
    assert _resolve_serve_buckets(args) == "1,8"
    args = build_parser().parse_args(
        ["bench_serve", "--requests", "10", "--concurrency", "2"])
    assert args.requests == 10


def test_bench_serve_cli_emits_json(capsys):
    import json

    from poseidon_tpu.runtime.cli import main

    assert main(["bench_serve", "--requests", "20", "--concurrency", "2",
                 "--buckets", "1,2,4", "--batch", "3"]) == 0
    line = capsys.readouterr().out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["metric"] == "serving_p99_ms"
    assert payload["ok"] == 20 and payload["unit"] == "ms"
    assert payload["p50_ms"] is not None and payload["throughput_rps"] > 0


def test_parse_buckets():
    from poseidon_tpu.serving.executor import parse_buckets

    assert parse_buckets("1,4,16,64") == (1, 4, 16, 64)
    assert parse_buckets("8,2") == (2, 8)
    with pytest.raises(ValueError):
        parse_buckets("0,2")
    with pytest.raises(ValueError):
        parse_buckets("a,b")
