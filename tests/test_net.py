import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.core.net import Net, filter_net
from poseidon_tpu.models import zoo
from poseidon_tpu.proto import load_net_from_string
from poseidon_tpu.proto.messages import NetState


def _batch(shapes, rng):
    data = rng.randn(*shapes["data"]).astype(np.float32)
    label = rng.randint(0, 10, size=shapes["label"])
    return {"data": jnp.asarray(data), "label": jnp.asarray(label)}


def test_lenet_shapes_and_forward(rng_np):
    net = Net(zoo.lenet(), phase="TRAIN", source_shapes=zoo.lenet_shapes(4))
    assert net.blob_shapes["conv1"] == (4, 20, 24, 24)
    assert net.blob_shapes["pool1"] == (4, 20, 12, 12)
    assert net.blob_shapes["conv2"] == (4, 50, 8, 8)
    assert net.blob_shapes["ip1"] == (4, 500)
    assert net.blob_shapes["ip2"] == (4, 10)
    params = net.init(jax.random.PRNGKey(0))
    assert params["conv1"]["w"].shape == (20, 1, 5, 5)
    assert params["ip1"]["w"].shape == (500, 800)
    out = net.apply(params, _batch(zoo.lenet_shapes(4), rng_np),
                    rng=jax.random.PRNGKey(1))
    assert out.loss.shape == ()
    assert float(out.loss) == pytest.approx(np.log(10), rel=0.3)


def test_phase_filtering():
    net_param = zoo.lenet(with_accuracy=True)
    train = filter_net(net_param, NetState(phase="TRAIN"))
    test = filter_net(net_param, NetState(phase="TEST"))
    train_names = [l.name for l in train]
    test_names = [l.name for l in test]
    assert "accuracy" not in train_names
    assert "accuracy" in test_names


def test_grad_flows_everywhere(rng_np):
    net = Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
              source_shapes=zoo.lenet_shapes(2))
    params = net.init(jax.random.PRNGKey(0))
    batch = _batch(zoo.lenet_shapes(2), rng_np)

    def loss_fn(p):
        return net.apply(p, batch, rng=jax.random.PRNGKey(0)).loss

    grads = jax.grad(loss_fn)(params)
    for lname, lg in grads.items():
        for pname, g in lg.items():
            assert np.isfinite(np.asarray(g)).all(), (lname, pname)
            assert np.abs(np.asarray(g)).sum() > 0, (lname, pname)


def test_inplace_layers(rng_np):
    # relu1 writes its bottom in place (top == bottom), the Caffe idiom.
    net = Net(zoo.cifar10_quick(), phase="TRAIN",
              source_shapes=zoo.cifar10_shapes(2))
    params = net.init(jax.random.PRNGKey(0))
    out = net.apply(params, _batch(zoo.cifar10_shapes(2), rng_np),
                    rng=jax.random.PRNGKey(1), keep_blobs=True)
    assert np.asarray(out.blobs["pool1"]).min() >= 0  # post-relu view


def test_deploy_net_with_input_decl(rng_np):
    net_param = load_net_from_string("""
    name: "deploy"
    input: "data"
    input_dim: 2 input_dim: 3 input_dim: 8 input_dim: 8
    layers { name: "conv" type: CONVOLUTION bottom: "data" top: "conv"
      convolution_param { num_output: 4 kernel_size: 3
        weight_filler { type: "xavier" } } }
    layers { name: "prob" type: SOFTMAX bottom: "conv" top: "prob" }
    """)
    net = Net(net_param, phase="TEST")
    assert net.blob_shapes["prob"] == (2, 4, 6, 6)
    params = net.init(jax.random.PRNGKey(0))
    x = jnp.asarray(rng_np.randn(2, 3, 8, 8).astype(np.float32))
    out = net.apply(params, {"data": x})
    np.testing.assert_allclose(
        np.asarray(out.outputs["prob"]).sum(axis=1), 1.0, rtol=1e-5)


def test_cifar10_full_builds_and_steps():
    """cifar10_full (pool-before-relu, WITHIN_CHANNEL LRN, decay 250 ip):
    builds, one train step moves params, loss ~ ln(10)."""
    import jax
    from poseidon_tpu.parallel import (CommConfig, build_train_step,
                                       init_train_state, make_mesh)
    from poseidon_tpu.proto.messages import SolverParameter

    net = Net(zoo.cifar10_full(), phase="TRAIN",
              source_shapes=zoo.cifar10_shapes(2))
    assert net.layers[3].lp.lrn_param.norm_region == "WITHIN_CHANNEL"
    sp = SolverParameter(base_lr=0.001, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.004)
    ts = build_train_step(net, sp, make_mesh(), CommConfig(), donate=False)
    params = net.init(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    batch = {"data": jnp.asarray(rs.rand(16, 3, 32, 32).astype(np.float32)),
             "label": jnp.asarray(rs.randint(0, 10, size=(16,)))}
    p, s, m = ts.step(params, init_train_state(params), batch,
                      jax.random.PRNGKey(1))
    assert float(m["loss"]) == pytest.approx(np.log(10), rel=0.3)
    assert np.abs(np.asarray(p["ip1"]["w"]) -
                  np.asarray(params["ip1"]["w"])).max() > 0


def test_googlenet_trains_multidevice():
    """GoogLeNet end-to-end on the 8-device mesh: aux heads (0.3 loss
    weights, train_test.prototxt parity) contribute to the total loss and
    all three heads report; one SGD step moves the deepest inception params.
    bf16 compute keeps the 224x224 CPU run tractable."""
    import jax
    from poseidon_tpu.config import policy_scope
    from poseidon_tpu.parallel import (CommConfig, build_train_step,
                                       init_train_state, make_mesh)
    from poseidon_tpu.proto.messages import SolverParameter

    net = Net(zoo.googlenet(num_classes=16), phase="TRAIN",
              source_shapes=zoo.googlenet_shapes(1))
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    mesh = make_mesh()
    with policy_scope(compute_dtype=jnp.bfloat16):
        ts = build_train_step(net, sp, mesh, CommConfig(), donate=False)
        params = net.init(jax.random.PRNGKey(0))
        w0 = np.asarray(params["inception_5b/1x1"]["w"])
        rs = np.random.RandomState(0)
        batch = {
            "data": jnp.asarray(rs.rand(8, 3, 224, 224).astype(np.float32)),
            "label": jnp.asarray(rs.randint(0, 16, size=(8,))),
        }
        p, s, m = ts.step(params, init_train_state(params), batch,
                          jax.random.PRNGKey(1))
    # total loss = main + 0.3*aux1 + 0.3*aux2 (all finite, all reported)
    assert np.isfinite(float(m["loss"]))
    assert {"loss1/loss", "loss2/loss", "loss3"} <= set(m), sorted(m)
    want = (float(m["loss3"]) + 0.3 * float(m["loss1/loss"])
            + 0.3 * float(m["loss2/loss"]))
    assert float(m["loss"]) == pytest.approx(want, rel=0.05)
    # ~ln(16) at init
    assert float(m["loss3"]) == pytest.approx(np.log(16), rel=0.4)
    assert np.abs(np.asarray(p["inception_5b/1x1"]["w"]) - w0).max() > 0


def test_googlenet_builds():
    net = Net(zoo.googlenet(num_classes=100), phase="TRAIN",
              source_shapes=zoo.googlenet_shapes(2))
    assert net.blob_shapes["inception_3a/output"] == (2, 256, 28, 28)
    assert net.blob_shapes["inception_5b/output"] == (2, 1024, 7, 7)
    assert net.blob_shapes["pool5/7x7_s1"] == (2, 1024, 1, 1)
    # three losses in TRAIN phase
    loss_layers = [l for l in net.layers if l.TYPE == "SOFTMAX_LOSS"]
    assert len(loss_layers) == 3


def test_alexnet_builds():
    net = Net(zoo.alexnet(), phase="TRAIN",
              source_shapes=zoo.alexnet_shapes(2))
    assert net.blob_shapes["pool5"] == (2, 256, 6, 6)
    assert net.param_count() > 60_000_000  # AlexNet ~61M params


def test_weight_export_import_roundtrip(rng_np):
    net = Net(zoo.lenet(), phase="TRAIN", source_shapes=zoo.lenet_shapes(2))
    params = net.init(jax.random.PRNGKey(0))
    exported = net.export_weights(params)
    params2 = net.init(jax.random.PRNGKey(42))
    params3 = net.load_weights(params2, exported)
    for l in exported:
        for pd, arr in zip(net.param_defs[l], exported[l]):
            np.testing.assert_array_equal(np.asarray(params3[l][pd.name]), arr)


def test_caffemodel_wire_roundtrip(rng_np, tmp_path):
    from poseidon_tpu.proto.wire import decode_caffemodel, encode_caffemodel
    net = Net(zoo.lenet(), phase="TRAIN", source_shapes=zoo.lenet_shapes(2))
    params = net.init(jax.random.PRNGKey(0))
    blob = encode_caffemodel("LeNet", net.export_weights(params))
    decoded = decode_caffemodel(blob)
    assert set(decoded) == set(net.param_defs)
    np.testing.assert_allclose(
        decoded["conv1"][0].reshape(20, 1, 5, 5),
        np.asarray(params["conv1"]["w"]), rtol=1e-6)


def test_shared_weights_siamese():
    """Caffe's named-param sharing (siamese pattern): two branches share conv
    weights via `param:` names; gradients flow through both uses."""
    from poseidon_tpu.proto.messages import load_net_from_string
    net_param = load_net_from_string("""
    name: "siamese"
    layers { name: "ip_a" type: INNER_PRODUCT bottom: "xa" top: "fa"
      param: "shared_w" param: "shared_b"
      inner_product_param { num_output: 6 weight_filler { type: "xavier" } } }
    layers { name: "ip_b" type: INNER_PRODUCT bottom: "xb" top: "fb"
      param: "shared_w" param: "shared_b"
      inner_product_param { num_output: 6 weight_filler { type: "xavier" } } }
    layers { name: "loss" type: CONTRASTIVE_LOSS
      bottom: "fa" bottom: "fb" bottom: "sim" top: "loss"
      contrastive_loss_param { margin: 1.0 } }
    """)
    shapes = {"xa": (4, 3), "xb": (4, 3), "sim": (4,)}
    net = Net(net_param, "TRAIN", source_shapes=shapes)
    # only the owner layer holds storage
    assert "ip_a" in net.param_defs and "ip_b" not in net.param_defs
    params = net.init(jax.random.PRNGKey(0))
    assert set(params) == {"ip_a"}

    rs = np.random.RandomState(0)
    batch = {"xa": jnp.asarray(rs.randn(4, 3).astype(np.float32)),
             "xb": jnp.asarray(rs.randn(4, 3).astype(np.float32)),
             "sim": jnp.asarray(np.array([1, 0, 1, 0], np.float32))}
    out = net.apply(params, batch, keep_blobs=True)
    # both branches used the same weights
    w = np.asarray(params["ip_a"]["w"])
    np.testing.assert_allclose(
        np.asarray(out.blobs["fb"]),
        np.asarray(batch["xb"]) @ w.T + np.asarray(params["ip_a"]["b"]),
        rtol=1e-5)

    # gradient accumulates from BOTH branches: zeroing one branch's input
    # changes the shared-weight gradient
    def loss_fn(p, b):
        return net.apply(p, b).loss

    g_both = jax.grad(loss_fn)(params, batch)
    batch_zero_b = dict(batch, xb=jnp.zeros_like(batch["xb"]))
    g_one = jax.grad(loss_fn)(params, batch_zero_b)
    assert np.abs(np.asarray(g_both["ip_a"]["w"])).sum() > 0
    assert not np.allclose(np.asarray(g_both["ip_a"]["w"]),
                           np.asarray(g_one["ip_a"]["w"]))

    # round trip: caffemodel export contains BOTH layers' blobs (Caffe's
    # serialization), and loading routes sharer blobs back to owner storage
    exported = net.export_weights(params)
    assert set(exported) == {"ip_a", "ip_b"}
    np.testing.assert_array_equal(exported["ip_a"][0], exported["ip_b"][0])
    reloaded = net.load_weights(net.init(jax.random.PRNGKey(9)), exported)
    np.testing.assert_array_equal(np.asarray(reloaded["ip_a"]["w"]), w)
