import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.core.net import Net, filter_net
from poseidon_tpu.models import zoo
from poseidon_tpu.proto import load_net_from_string
from poseidon_tpu.proto.messages import NetState


def _batch(shapes, rng):
    data = rng.randn(*shapes["data"]).astype(np.float32)
    label = rng.randint(0, 10, size=shapes["label"])
    return {"data": jnp.asarray(data), "label": jnp.asarray(label)}


def test_lenet_shapes_and_forward(rng_np):
    net = Net(zoo.lenet(), phase="TRAIN", source_shapes=zoo.lenet_shapes(4))
    assert net.blob_shapes["conv1"] == (4, 20, 24, 24)
    assert net.blob_shapes["pool1"] == (4, 20, 12, 12)
    assert net.blob_shapes["conv2"] == (4, 50, 8, 8)
    assert net.blob_shapes["ip1"] == (4, 500)
    assert net.blob_shapes["ip2"] == (4, 10)
    params = net.init(jax.random.PRNGKey(0))
    assert params["conv1"]["w"].shape == (20, 1, 5, 5)
    assert params["ip1"]["w"].shape == (500, 800)
    out = net.apply(params, _batch(zoo.lenet_shapes(4), rng_np),
                    rng=jax.random.PRNGKey(1))
    assert out.loss.shape == ()
    assert float(out.loss) == pytest.approx(np.log(10), rel=0.3)


def test_phase_filtering():
    net_param = zoo.lenet(with_accuracy=True)
    train = filter_net(net_param, NetState(phase="TRAIN"))
    test = filter_net(net_param, NetState(phase="TEST"))
    train_names = [l.name for l in train]
    test_names = [l.name for l in test]
    assert "accuracy" not in train_names
    assert "accuracy" in test_names


def test_grad_flows_everywhere(rng_np):
    net = Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
              source_shapes=zoo.lenet_shapes(2))
    params = net.init(jax.random.PRNGKey(0))
    batch = _batch(zoo.lenet_shapes(2), rng_np)

    def loss_fn(p):
        return net.apply(p, batch, rng=jax.random.PRNGKey(0)).loss

    grads = jax.grad(loss_fn)(params)
    for lname, lg in grads.items():
        for pname, g in lg.items():
            assert np.isfinite(np.asarray(g)).all(), (lname, pname)
            assert np.abs(np.asarray(g)).sum() > 0, (lname, pname)


def test_inplace_layers(rng_np):
    # relu1 writes its bottom in place (top == bottom), the Caffe idiom.
    net = Net(zoo.cifar10_quick(), phase="TRAIN",
              source_shapes=zoo.cifar10_shapes(2))
    params = net.init(jax.random.PRNGKey(0))
    out = net.apply(params, _batch(zoo.cifar10_shapes(2), rng_np),
                    rng=jax.random.PRNGKey(1), keep_blobs=True)
    assert np.asarray(out.blobs["pool1"]).min() >= 0  # post-relu view


def test_deploy_net_with_input_decl(rng_np):
    net_param = load_net_from_string("""
    name: "deploy"
    input: "data"
    input_dim: 2 input_dim: 3 input_dim: 8 input_dim: 8
    layers { name: "conv" type: CONVOLUTION bottom: "data" top: "conv"
      convolution_param { num_output: 4 kernel_size: 3
        weight_filler { type: "xavier" } } }
    layers { name: "prob" type: SOFTMAX bottom: "conv" top: "prob" }
    """)
    net = Net(net_param, phase="TEST")
    assert net.blob_shapes["prob"] == (2, 4, 6, 6)
    params = net.init(jax.random.PRNGKey(0))
    x = jnp.asarray(rng_np.randn(2, 3, 8, 8).astype(np.float32))
    out = net.apply(params, {"data": x})
    np.testing.assert_allclose(
        np.asarray(out.outputs["prob"]).sum(axis=1), 1.0, rtol=1e-5)


def test_googlenet_builds():
    net = Net(zoo.googlenet(num_classes=100), phase="TRAIN",
              source_shapes=zoo.googlenet_shapes(2))
    assert net.blob_shapes["inception_3a/output"] == (2, 256, 28, 28)
    assert net.blob_shapes["inception_5b/output"] == (2, 1024, 7, 7)
    assert net.blob_shapes["pool5/7x7_s1"] == (2, 1024, 1, 1)
    # three losses in TRAIN phase
    loss_layers = [l for l in net.layers if l.TYPE == "SOFTMAX_LOSS"]
    assert len(loss_layers) == 3


def test_alexnet_builds():
    net = Net(zoo.alexnet(), phase="TRAIN",
              source_shapes=zoo.alexnet_shapes(2))
    assert net.blob_shapes["pool5"] == (2, 256, 6, 6)
    assert net.param_count() > 60_000_000  # AlexNet ~61M params


def test_weight_export_import_roundtrip(rng_np):
    net = Net(zoo.lenet(), phase="TRAIN", source_shapes=zoo.lenet_shapes(2))
    params = net.init(jax.random.PRNGKey(0))
    exported = net.export_weights(params)
    params2 = net.init(jax.random.PRNGKey(42))
    params3 = net.load_weights(params2, exported)
    for l in exported:
        for pd, arr in zip(net.param_defs[l], exported[l]):
            np.testing.assert_array_equal(np.asarray(params3[l][pd.name]), arr)


def test_caffemodel_wire_roundtrip(rng_np, tmp_path):
    from poseidon_tpu.proto.wire import decode_caffemodel, encode_caffemodel
    net = Net(zoo.lenet(), phase="TRAIN", source_shapes=zoo.lenet_shapes(2))
    params = net.init(jax.random.PRNGKey(0))
    blob = encode_caffemodel("LeNet", net.export_weights(params))
    decoded = decode_caffemodel(blob)
    assert set(decoded) == set(net.param_defs)
    np.testing.assert_allclose(
        decoded["conv1"][0].reshape(20, 1, 5, 5),
        np.asarray(params["conv1"]["w"]), rtol=1e-6)
