"""LevelDB format reader/writer + snappy codec."""

import struct

import numpy as np
import pytest

from poseidon_tpu.data import snappy
from poseidon_tpu.data.leveldb_reader import (
    LOG_FULL, LevelDBReader, LevelDBWriter, TYPE_DELETION, TYPE_VALUE,
    crc32c, crc32c_masked)


def test_crc32c_known_vectors():
    # RFC 3720 test vector: 32 bytes of zeros -> 0x8a9136aa
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert crc32c(b"123456789") == 0xE3069283


def test_snappy_roundtrip_literals():
    rs = np.random.RandomState(0)
    for n in [0, 1, 59, 60, 61, 300, 70000]:
        data = rs.bytes(n)
        assert snappy.uncompress(snappy.compress(data)) == data


def test_snappy_copy_elements():
    # hand-crafted: literal "abcd" then copy-1 (len 4 -> (4-4)=0 in bits 2..4,
    # offset 4) -> "abcdabcd"
    blob = bytes([8]) + bytes([3 << 2]) + b"abcd" + bytes([1, 4])
    assert snappy.uncompress(blob) == b"abcdabcd"
    # overlapping copy: literal "ab", copy-1 len 6 ((6-4)=2) offset 2
    blob2 = bytes([8]) + bytes([1 << 2]) + b"ab" + bytes([(2 << 2) | 1, 2])
    assert snappy.uncompress(blob2) == b"abababab"


@pytest.mark.parametrize("compress", [False, True])
def test_leveldb_write_read_roundtrip(tmp_path, compress):
    path = str(tmp_path / "db")
    w = LevelDBWriter(path, compress=compress)
    rs = np.random.RandomState(0)
    values = {}
    for i in range(500):  # multiple blocks
        key = f"{i:08d}".encode()
        val = rs.bytes(rs.randint(20, 400))
        values[key] = val
        w.put(key, val)
    w.close()

    r = LevelDBReader(path)
    assert len(r) == 500
    got = dict(iter(r))
    assert got == values
    assert [r.key_at(i) for i in range(3)] == sorted(values)[:3]
    assert r.value_at(0) == values[sorted(values)[0]]


def test_leveldb_log_replay_and_deletions(tmp_path):
    """A log-only database (never compacted): entries live in the WAL."""
    path = tmp_path / "db"
    path.mkdir()
    # WriteBatch: seq=1, count=3: put a=1, put b=2, delete a
    batch = bytearray()
    batch += struct.pack("<Q", 1) + struct.pack("<I", 3)
    for op, key, val in [(TYPE_VALUE, b"a", b"1"), (TYPE_VALUE, b"b", b"2"),
                         (TYPE_DELETION, b"a", None)]:
        batch.append(op)
        batch.append(len(key))
        batch += key
        if val is not None:
            batch.append(len(val))
            batch += val
    payload = bytes(batch)
    header = struct.pack("<IHB", crc32c_masked(bytes([LOG_FULL]) + payload),
                         len(payload), LOG_FULL)
    (path / "000003.log").write_bytes(header + payload)

    r = LevelDBReader(str(path))
    assert len(r) == 1
    assert dict(iter(r)) == {b"b": b"2"}


def test_leveldb_datum_source(tmp_path):
    from poseidon_tpu.data.leveldb_reader import LevelDBWriter
    from poseidon_tpu.data.sources import LevelDBSource
    from poseidon_tpu.proto.wire import Datum, encode_datum

    path = str(tmp_path / "db")
    w = LevelDBWriter(path)
    rs = np.random.RandomState(1)
    for i in range(12):
        arr = rs.randint(0, 255, size=(3, 5, 5)).astype(np.uint8)
        w.put(f"{i:08d}".encode(),
              encode_datum(Datum(3, 5, 5, arr.tobytes(), label=i)))
    w.close()
    src = LevelDBSource(path)
    assert len(src) == 12
    arr, label = src.read(7)
    assert arr.shape == (3, 5, 5) and label == 7


def test_data_layer_leveldb_backend(tmp_path):
    from poseidon_tpu.data.leveldb_reader import LevelDBWriter
    from poseidon_tpu.data.pipeline import BatchPipeline
    from poseidon_tpu.proto.messages import DataParameter, LayerParameter
    from poseidon_tpu.proto.wire import Datum, encode_datum

    path = str(tmp_path / "db")
    w = LevelDBWriter(path)
    rs = np.random.RandomState(2)
    for i in range(20):
        arr = rs.randint(0, 255, size=(1, 6, 6)).astype(np.uint8)
        w.put(f"{i:08d}".encode(),
              encode_datum(Datum(1, 6, 6, arr.tobytes(), label=i % 4)))
    w.close()
    lp = LayerParameter(
        name="d", type="DATA", top=["data", "label"],
        data_param=DataParameter(source=path, batch_size=5))  # default backend
    pipe = BatchPipeline(lp, "TRAIN", 5)
    b = next(pipe)
    assert b["data"].shape == (5, 1, 6, 6)
    pipe.close()


def test_convert_db_roundtrip(tmp_path):
    from poseidon_tpu.data.leveldb_reader import LevelDBWriter
    from poseidon_tpu.data.lmdb_reader import LMDBReader
    from poseidon_tpu.runtime.tools import convert_db

    src = str(tmp_path / "ldb")
    w = LevelDBWriter(src)
    for i in range(10):
        w.put(f"{i:04d}".encode(), f"value{i}".encode())
    w.close()

    out = str(tmp_path / "mdb")
    assert convert_db(src, out, "LMDB") == 10
    r = LMDBReader(out)
    assert len(r) == 10
    assert r.value_at(3) == b"value3"

    back = str(tmp_path / "ldb2")
    assert convert_db(out, back, "LEVELDB") == 10
    from poseidon_tpu.data.leveldb_reader import LevelDBReader
    r2 = LevelDBReader(back)
    assert dict(iter(r2)) == {f"{i:04d}".encode(): f"value{i}".encode()
                              for i in range(10)}
