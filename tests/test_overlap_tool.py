"""The DWBP overlap analyzer end-to-end on an in-process CPU trace.

scripts/analyze_overlap.py is the hardware-evidence tool (xplane ->
collective/compute co-run fraction); this test validates the whole chain —
trace capture, xplane proto parsing, event classification, interval math —
so the only thing left to vary on real TPU is the numbers.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))


def test_overlap_fraction_interval_math():
    from analyze_overlap import overlap_fraction
    # one collective [10, 20) with compute covering [0, 15) => 50% overlap
    events = [
        ("psum.1", 10, 10),          # collective, dur 10
        ("fusion.2", 0, 15),         # compute
        ("$python_frame", 0, 100),   # filtered
        ("end: psum.1", 10, 10),     # filtered end-marker
    ]
    out = overlap_fraction(events)
    assert out["n_collectives"] == 1
    assert out["value"] == pytest.approx(0.5)


def test_overlap_tool_on_real_trace(tmp_path):
    import jax
    import jax.numpy as jnp
    from poseidon_tpu.compat import shard_map
    from jax import lax
    from jax.sharding import Mesh, PartitionSpec as P

    pytest.importorskip("tensorflow.tsl.profiler.protobuf.xplane_pb2")

    mesh = Mesh(np.array(jax.devices()), ("data",))

    def f(x):
        g = jnp.tanh(x) @ jnp.ones((256, 256), x.dtype)
        return lax.psum(g, "data").sum()

    step = jax.jit(shard_map(f, mesh=mesh, in_specs=P("data"),
                                 out_specs=P(), check_vma=False))
    x = jnp.ones((16, 256))
    step(x).block_until_ready()
    trace = str(tmp_path / "trace")
    jax.profiler.start_trace(trace)
    for _ in range(2):
        r = step(x)
    r.block_until_ready()
    jax.profiler.stop_trace()

    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "analyze_overlap.py"),
         trace],
        capture_output=True, text=True, timeout=300)
    out = json.loads(res.stdout.strip().splitlines()[-1])
    assert out["n_collectives"] > 0, out
    assert out["value"] is not None and 0.0 <= out["value"] <= 1.0
    assert res.returncode == 0
