"""Static layout test: NHWC boundary transposes must cancel in the HLO.

Round-4 commit ef62b27 extended the channels-last policy to pooling/LRN so
the conv->relu->lrn->pool->conv chain stays NHWC end to end; the claim that
"boundary transposes are exact inverses and cancel in XLA" was never pinned
by a test, and the only hardware A/B (round 3, pre-fix) measured 0.53x —
i.e. the transposes did NOT cancel when pool/LRN stayed NCHW. This applies
the test_hlo_comm.py pattern (assert on the compiled program, not on our
intent) to layout: count `transpose` ops in the optimized HLO of the chain
under both layout policies. A future regression that strands a layout
change mid-chain reappears as a transpose-count jump, caught on CPU.

Reference anchor: the cuDNN NCHW-native layers this policy replaces
(src/caffe/layers/cudnn_conv_layer.cpp); the TPU-first design instead picks
XLA's preferred channels-last layout and keeps the public interface NCHW.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np

from poseidon_tpu import config
from poseidon_tpu.ops import nn

B, C, H, W = 4, 3, 31, 31
C1, C2 = 16, 32


def _chain(x, w1, b1, w2, b2):
    """AlexNet's stem order: conv -> relu -> lrn -> pool -> conv."""
    y = nn.conv2d(x, w1, b1, stride=(2, 2), pad=(1, 1))
    y = jax.nn.relu(y)
    y = nn.lrn_across_channels(y, local_size=5, alpha=1e-4, beta=0.75)
    y = nn.max_pool(y, kernel=(3, 3), stride=(2, 2), pad=(0, 0))
    return nn.conv2d(y, w2, b2, stride=(1, 1), pad=(1, 1))


def _inputs():
    rs = np.random.RandomState(0)
    return (jnp.asarray(rs.randn(B, C, H, W).astype(np.float32)),
            jnp.asarray(rs.randn(C1, C, 3, 3).astype(np.float32)),
            jnp.asarray(rs.randn(C1).astype(np.float32)),
            jnp.asarray(rs.randn(C2, C1, 3, 3).astype(np.float32)),
            jnp.asarray(rs.randn(C2).astype(np.float32)))


def _n_transposes(fn, *args, layout: str) -> int:
    with config.policy_scope(conv_layout=layout):
        hlo = jax.jit(fn).lower(*args).compile().as_text()
    # count transpose OPS (incl. inside fusion bodies), not the word in
    # metadata: an HLO instruction line is `%x = f32[...]{...} transpose(`
    return len(re.findall(r"= [a-z0-9\[\]{},]+ transpose\(", hlo))


def test_nhwc_forward_boundary_transposes_cancel():
    """Forward chain: every op-boundary transpose pair between consecutive
    channels-last ops must cancel, leaving only the chain's entry/exit
    (<= 2 more than the NCHW build, which needs none of them)."""
    args = _inputs()
    n_nchw = _n_transposes(_chain, *args, layout="NCHW")
    n_nhwc = _n_transposes(_chain, *args, layout="NHWC")
    # 5 channels-last ops x 2 boundary transposes each = 10 written; all
    # interior pairs must cancel. Allow entry + exit only.
    assert n_nhwc <= n_nchw + 2, (
        f"NHWC chain keeps {n_nhwc} transposes vs {n_nchw} for NCHW — "
        f"boundary transposes are NOT cancelling (ef62b27 regression: some "
        f"op in the chain fell back to NCHW mid-stream)")


def test_nhwc_backward_boundary_transposes_cancel():
    """Same property through the VJP: the cotangent chain re-traverses every
    boundary, so a stranded mid-chain layout change doubles up here."""
    args = _inputs()

    def loss(x, w1, b1, w2, b2):
        return jnp.sum(_chain(x, w1, b1, w2, b2) ** 2)

    g = jax.grad(loss, argnums=(1, 2, 3, 4))
    n_nchw = _n_transposes(g, *args, layout="NCHW")
    n_nhwc = _n_transposes(g, *args, layout="NHWC")
    # forward entry/exit + their backward mirrors; weight-grad convs may
    # each keep one layout change that has no inverse partner
    assert n_nhwc <= n_nchw + 6, (
        f"NHWC backward keeps {n_nhwc} transposes vs {n_nchw} for NCHW")


def test_nhwc_chain_is_channels_last_inside():
    """The convolutions must actually RUN channels-last under the policy:
    the optimized HLO's convolution ops carry f32[N,H,W,C]-shaped operands
    (minor-most channels), not just reordered metadata."""
    args = _inputs()
    with config.policy_scope(conv_layout="NHWC"):
        hlo = jax.jit(_chain).lower(*args).compile().as_text()
    conv_lines = [ln for ln in hlo.splitlines() if "convolution" in ln
                  and "dim_labels" in ln]
    assert conv_lines, "no convolution ops in compiled chain"
    for ln in conv_lines:
        m = re.search(r"dim_labels=([a-z0-9]+_[a-z0-9]+->[a-z0-9]+)", ln)
        if m:
            assert m.group(1).startswith("b01f"), (
                f"conv not channels-last under NHWC policy: {ln.strip()}")


def test_nhwc_numerics_match_nchw():
    """Layout is a performance policy, never a numerics change."""
    args = _inputs()
    with config.policy_scope(conv_layout="NCHW"):
        ref = jax.jit(_chain)(*args)
    with config.policy_scope(conv_layout="NHWC"):
        out = jax.jit(_chain)(*args)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                               rtol=2e-5, atol=2e-5)
