"""Static layout tests: the net-level NHWC plan is transpose-free inside.

Round 6 replaced the per-op transpose shims (round 3/5: transpose at every
op boundary and hope XLA cancels the pairs — it measurably did not across
pool/LRN/concat seams, the 0.53x NHWC A/B) with a net-level layout plan:
the whole graph runs channels-last and converts only at genuine
boundaries. These tests pin that claim on the COMPILER INPUT (StableHLO of
the lowered program): the layout transposes our program asks for must sit
only at the FC-flatten boundaries — never one pair per spatial op.

The count is taken at the StableHLO level via ``runtime/hlo_layout.py``
because the CPU backend's optimized HLO materializes its own conv
canonicalization transposes for every conv GRADIENT regardless of our
plan (~77 for the NCHW AlexNet step); the TPU-compiler (optimized-HLO)
version of this check is ``scripts/aot_tpu_check.py`` section ``nhwc``,
AOT against an abstract v5e.

Reference anchor: the cuDNN NCHW-native layers this policy replaces
(src/caffe/layers/cudnn_conv_layer.cpp); the TPU-first design instead
plans XLA's preferred channels-last layout and keeps the public interface
NCHW.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.core.net import Net
from poseidon_tpu.models import zoo
from poseidon_tpu.ops import nn
from poseidon_tpu.runtime import hlo_layout as HL


def _stablehlo_of(fn, *args):
    return jax.jit(fn).lower(*args).as_text()


# --------------------------------------------------------------------------- #
# op-level: the native NHWC chain emits ZERO transposes at the compiler input
# --------------------------------------------------------------------------- #

def test_native_nhwc_chain_has_zero_transposes():
    """conv -> (fused relu) -> lrn -> pool -> conv, built natively NHWC
    with canonical OIHW weights: not a single transpose reaches the
    compiler — there are no shims left to cancel."""
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, 31, 31, 3).astype(np.float32))
    w1 = jnp.asarray(rs.randn(16, 3, 3, 3).astype(np.float32))
    b1 = jnp.asarray(rs.randn(16).astype(np.float32))
    w2 = jnp.asarray(rs.randn(32, 16, 3, 3).astype(np.float32))
    b2 = jnp.asarray(rs.randn(32).astype(np.float32))

    def chain(x, w1, b1, w2, b2):
        y = nn.conv2d(x, w1, b1, (2, 2), (1, 1), layout="NHWC", act="relu")
        y = nn.lrn_across_channels(y, 5, 1e-4, 0.75, layout="NHWC")
        y = nn.max_pool(y, (3, 3), (2, 2), (0, 0), layout="NHWC")
        return nn.conv2d(y, w2, b2, (1, 1), (1, 1), layout="NHWC")

    txt = _stablehlo_of(chain, x, w1, b1, w2, b2)
    assert HL.count_layout_transposes(txt) == 0, HL.layout_report(txt)


def test_native_nhwc_chain_backward_has_zero_transposes():
    """Same property through the VJP: conv/pool/LRN gradients stay
    channels-last (jax's conv transpose rules juggle dimension numbers,
    not transposes)."""
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 15, 15, 3).astype(np.float32))
    w = jnp.asarray(rs.randn(8, 3, 3, 3).astype(np.float32))
    b = jnp.asarray(rs.randn(8).astype(np.float32))

    def loss(x, w, b):
        y = nn.conv2d(x, w, b, (1, 1), (1, 1), layout="NHWC", act="relu")
        y = nn.lrn_across_channels(y, 3, 1e-4, 0.75, layout="NHWC")
        y = nn.max_pool(y, (3, 3), (2, 2), (0, 0), layout="NHWC")
        return jnp.sum(y ** 2)

    txt = _stablehlo_of(jax.grad(loss, argnums=(0, 1, 2)), x, w, b)
    assert HL.count_layout_transposes(txt) == 0, HL.layout_report(txt)


# --------------------------------------------------------------------------- #
# net-level: full optimizer steps, layout transposes only at FC boundaries
# --------------------------------------------------------------------------- #

def _alexnet(layout, image=227, batch=2):
    return Net(zoo.alexnet(num_classes=10, with_accuracy=False), "TRAIN",
               {"data": (batch, 3, image, image), "label": (batch,)},
               conv_layout=layout)


def test_alexnet_nhwc_train_step_le_2_layout_transposes():
    """The acceptance bound: one full AlexNet optimizer step planned NHWC
    and fed NHWC keeps <= 2 layout transposes — the fc6 flatten boundary's
    forward + backward pair and NOTHING else (the shim design carried one
    surviving pair per pool/LRN seam)."""
    net = _alexnet("NHWC")
    rep = HL.net_transpose_report(net, per_dev_batch=2, image=227)
    assert rep["layout_transposes"] <= 2, rep
    # and each of them is the pool5 <-> fc6 boundary (256-channel 6x6)
    for t in rep["layout_transpose_shapes"]:
        assert sorted(t["shape"])[-1] == 256, rep


def test_alexnet_transpose_count_is_depth_independent():
    """The regression the ISSUE targets: under the old shim the count grew
    with every spatial op (one pair per pool/LRN seam). Net-level planning
    makes it a function of the BOUNDARY count only — AlexNet has 5 convs,
    3 pools, 2 LRNs and still exactly one convert site."""
    rep = HL.net_transpose_report(_alexnet("NHWC"), per_dev_batch=2,
                                  image=227)
    n_spatial_ops = 5 + 3 + 2
    assert rep["layout_transposes"] < n_spatial_ops, rep


def test_googlenet_nhwc_transposes_only_at_fc_boundaries():
    """GoogLeNet has THREE genuine FC boundaries (main head's global pool
    is degenerate 1x1; two aux heads flatten real 4x4x128 blobs): <= 2
    layout transposes per boundary, zero anywhere in the 9-inception
    conv/pool/concat body."""
    net = Net(zoo.googlenet(num_classes=10, with_accuracy=False), "TRAIN",
              {"data": (1, 3, 224, 224), "label": (1,)},
              conv_layout="NHWC")
    rep = HL.net_transpose_report(net, per_dev_batch=1, image=224)
    n_boundaries = 3  # loss3/classifier + two aux-head FCs
    assert rep["layout_transposes"] <= 2 * n_boundaries, rep
    # every surviving transpose is at an FC flatten (4x4x128 aux or the
    # degenerate 1x1x1024 main head) — none inside the inception body
    for t in rep["layout_transpose_shapes"]:
        assert max(t["shape"]) in (128, 1024), rep


def test_nchw_plan_has_zero_layout_transposes():
    """The canonical plan is the identity: no layout machinery leaks in."""
    rep = HL.net_transpose_report(_alexnet("NCHW"), per_dev_batch=2,
                                  image=227)
    assert rep["layout_transposes"] == 0, rep


def test_nhwc_plan_fed_canonical_costs_exactly_one_entry_transpose():
    """Feeding the Caffe NCHW contract into an NHWC-planned net costs one
    entry transpose per image input on top of the boundary pair — the
    documented fallback, not a regression."""
    net = _alexnet("NHWC")
    from poseidon_tpu.proto.messages import SolverParameter
    step = HL.build_plain_step(net, SolverParameter(
        base_lr=0.01, lr_policy="fixed", momentum=0.9), input_layout="NCHW")
    params, state, _, rng = HL.step_avals(net, 2, 227)
    batch = {"data": jax.ShapeDtypeStruct((2, 3, 227, 227), jnp.float32),
             "label": jax.ShapeDtypeStruct((2,), jnp.int32)}
    txt = jax.jit(step).lower(params, state, batch, rng).as_text()
    n = HL.count_layout_transposes(txt)
    # lower bound is the LIVE positive control for the parser: if a jax
    # upgrade changes the textual transpose form, every <= N assertion in
    # this file would pass vacuously — this program is GUARANTEED to carry
    # the entry transpose, so a zero count means the regex went blind
    assert 1 <= n <= 3, HL.layout_report(txt)


# --------------------------------------------------------------------------- #
# parser unit coverage
# --------------------------------------------------------------------------- #

def test_parser_reads_both_program_levels():
    hlo = ("  %t = f32[4,6,6,256]{3,2,1,0} transpose(%p), "
           "dimensions={0,3,1,2}\n"
           "  %u = f32[4,1,1,256]{3,2,1,0} transpose(%q), "
           "dimensions={0,3,1,2}\n")
    shlo = ("    %1 = stablehlo.transpose %0, dims = [0, 3, 1, 2] : "
            "(tensor<4x6x6x256xf32>) -> tensor<4x256x6x6xf32>\n")
    ops = HL.parse_transposes(hlo)
    assert len(ops) == 2
    assert ops[0].is_layout            # real 6x6x256 layout change
    assert not ops[1].nontrivial       # degenerate (N,1,1,C): a bitcast
    assert HL.count_layout_transposes(hlo) == 1
    assert HL.count_layout_transposes(shlo) == 1


@pytest.mark.parametrize("layout", ["NCHW", "NHWC"])
def test_report_carries_level_and_plan(layout):
    net = _alexnet(layout, image=67)
    rep = HL.net_transpose_report(net, per_dev_batch=2, image=67)
    assert rep["level"] == "stablehlo"
    assert rep["conv_layout"] == layout
