"""Native data plane vs Python reference: bit-parity and throughput sanity."""

import numpy as np
import pytest

from poseidon_tpu.data.lmdb_reader import LMDBWriter
from poseidon_tpu.proto.wire import Datum, encode_datum

native = pytest.importorskip("poseidon_tpu.data.native")

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def datum_db(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("db") / "lmdb")
    w = LMDBWriter(path)
    rs = np.random.RandomState(0)
    arrays, labels = [], []
    for i in range(64):
        arr = rs.randint(0, 255, size=(3, 12, 12)).astype(np.uint8)
        label = int(rs.randint(0, 10))
        arrays.append(arr)
        labels.append(label)
        w.put(f"{i:08d}".encode(),
              encode_datum(Datum(3, 12, 12, arr.tobytes(), label=label)))
    w.close()
    return path, arrays, labels


def test_native_reads_match_python(datum_db):
    path, arrays, labels = datum_db
    b = native.NativeLMDBBatcher(path, train=False)
    assert len(b) == 64
    assert b.record_shape == (3, 12, 12)
    data, got_labels = b.batch(np.arange(64))
    for i in range(64):
        np.testing.assert_array_equal(data[i], arrays[i].astype(np.float32))
        assert got_labels[i] == labels[i]
    b.close()


def test_native_transform_matches_python(datum_db):
    path, arrays, labels = datum_db
    mean_values = np.asarray([10.0, 20.0, 30.0], np.float32)
    b = native.NativeLMDBBatcher(path, crop_size=8, train=False, scale=0.5,
                                 mean_values=mean_values)
    data, _ = b.batch(np.asarray([5]))
    # center crop offset (12-8)//2 = 2
    src = arrays[5].astype(np.float32)[:, 2:10, 2:10]
    want = (src - mean_values[:, None, None]) * 0.5
    np.testing.assert_allclose(data[0], want, rtol=1e-6)
    b.close()


def test_native_full_mean_array(datum_db):
    path, arrays, _ = datum_db
    rs = np.random.RandomState(1)
    mean = rs.rand(3, 12, 12).astype(np.float32)
    b = native.NativeLMDBBatcher(path, crop_size=6, train=False, mean=mean)
    data, _ = b.batch(np.asarray([0]))
    src = arrays[0].astype(np.float32)
    off = (12 - 6) // 2
    want = (src - mean)[:, off:off + 6, off:off + 6]
    np.testing.assert_allclose(data[0], want, rtol=1e-5)
    b.close()


def test_native_train_crops_are_valid_windows(datum_db):
    path, arrays, _ = datum_db
    b = native.NativeLMDBBatcher(path, crop_size=8, mirror=True, train=True)
    data, _ = b.batch(np.arange(8), seed=7)
    for i in range(8):
        src = arrays[i].astype(np.float32)
        ok = False
        for ho in range(5):
            for wo in range(5):
                win = src[:, ho:ho + 8, wo:wo + 8]
                if np.allclose(data[i], win) or \
                        np.allclose(data[i], win[:, :, ::-1]):
                    ok = True
        assert ok, f"record {i}: output is not a crop/mirror of the source"
    # determinism: same seed -> same batch
    data2, _ = b.batch(np.arange(8), seed=7)
    np.testing.assert_array_equal(data, data2)
    # different seed -> different crops (with overwhelming probability)
    data3, _ = b.batch(np.arange(8), seed=8)
    assert not np.array_equal(data, data3)
    b.close()


def test_pipeline_uses_native_for_lmdb_data_layer(datum_db):
    path, _, _ = datum_db
    from poseidon_tpu.data.pipeline import BatchPipeline
    from poseidon_tpu.proto.messages import DataParameter, LayerParameter

    lp = LayerParameter(
        name="d", type="DATA", top=["data", "label"],
        data_param=DataParameter(source=path, batch_size=16, backend="LMDB"))
    pipe = BatchPipeline(lp, "TRAIN", 16)
    assert pipe.native is not None, "native path should engage for LMDB DATA"
    batch = next(pipe)
    assert batch["data"].shape == (16, 3, 12, 12)
    assert batch["label"].dtype == np.int32
    pipe.close()

    # forced Python path produces identically-shaped batches
    pipe_py = BatchPipeline(lp, "TRAIN", 16, use_native=False)
    batch_py = next(pipe_py)
    assert batch_py["data"].shape == batch["data"].shape
    pipe_py.close()


def test_native_snappy_matches_python():
    """The C++ decoder (pdp_snappy_uncompress) against the pure-Python codec
    on literals, hand-crafted copy elements, and malformed streams."""
    from poseidon_tpu.data import snappy
    from poseidon_tpu.data.native import available, snappy_uncompress
    if not available():
        import pytest
        pytest.skip("native dataplane not built")
    rs = np.random.RandomState(1)
    for n in [0, 1, 60, 300, 70000]:
        comp = snappy.compress(rs.bytes(n))
        assert snappy_uncompress(comp) == snappy._uncompress_py(comp)
    # copy-1 back-reference incl. overlapping RLE-style copy
    blob = bytes([8]) + bytes([3 << 2]) + b"abcd" + bytes([1, 4])
    assert snappy_uncompress(blob) == b"abcdabcd"
    blob2 = bytes([8]) + bytes([1 << 2]) + b"ab" + bytes([(2 << 2) | 1, 2])
    assert snappy_uncompress(blob2) == b"abababab"
    # copy-2: literal "xy", copy len 3 offset 2 via 2-byte offset
    blob3 = bytes([5]) + bytes([1 << 2]) + b"xy" + \
        bytes([((3 - 1) << 2) | 2, 2, 0])
    assert snappy_uncompress(blob3) == b"xyxyx"
    # malformed: declared length never produced
    import pytest
    with pytest.raises(ValueError):
        snappy_uncompress(bytes([200, 1]) + bytes([3 << 2]) + b"abcd")


def test_native_u8_matches_f32_pixels(datum_db):
    """batch_u8 (device-transform ingest) must pick the SAME crop/mirror
    windows as batch under the same seed — only the mean/scale (moved
    on-device) and dtype differ."""
    path, _, _ = datum_db
    b = native.NativeLMDBBatcher(path, crop_size=8, mirror=True, train=True)
    assert b.supports_u8()
    f32, l1 = b.batch(np.arange(16), seed=11)
    u8, l2 = b.batch_u8(np.arange(16), seed=11)
    assert u8.dtype == np.uint8
    np.testing.assert_array_equal(u8.astype(np.float32), f32)
    np.testing.assert_array_equal(l1, l2)
    b.close()


def test_pipeline_device_transform_spec(datum_db):
    """device_transform: uint8 batches + the {mean, scale} spec the step
    must apply; a mean_file config keeps the host path (per-sample crop
    alignment of the full mean cannot be reproduced on device)."""
    path, _, _ = datum_db
    from poseidon_tpu.data.pipeline import BatchPipeline
    from poseidon_tpu.proto.messages import (DataParameter, LayerParameter,
                                             TransformationParameter)

    lp = LayerParameter(
        name="d", type="DATA", top=["data", "label"],
        data_param=DataParameter(source=path, batch_size=8, backend="LMDB"),
        transform_param=TransformationParameter(
            crop_size=8, mirror=True, scale=0.00390625,
            mean_value=[33.0, 34.0, 35.0]))
    pipe = BatchPipeline(lp, "TRAIN", 8, device_transform=True)
    assert pipe.device_transform_spec is not None
    batch = next(pipe)
    assert batch["data"].dtype == np.uint8
    spec = pipe.device_transform_spec
    np.testing.assert_array_equal(spec["mean_values"], [33.0, 34.0, 35.0])
    assert abs(spec["scale"] - 0.00390625) < 1e-12
    pipe.close()

    # host path and device path agree end to end (same seed): the uint8
    # batch put through the spec equals the host-transformed batch
    pipe_h = BatchPipeline(lp, "TRAIN", 8, device_transform=False)
    host = next(pipe_h)
    dev = (batch["data"].astype(np.float32)
           - np.asarray(spec["mean_values"])[None, :, None, None]) \
        * spec["scale"]
    np.testing.assert_allclose(dev, host["data"], rtol=1e-6, atol=1e-6)
    pipe_h.close()


def test_pipeline_device_transform_falls_back_for_float_data(tmp_path):
    """float_data Datums cannot ship as uint8: the init-time probe must
    disable the u8 path (host f32 transform) instead of crashing the
    prefetch worker on the first batch."""
    from poseidon_tpu.data.lmdb_reader import LMDBWriter
    from poseidon_tpu.data.pipeline import BatchPipeline
    from poseidon_tpu.proto.messages import DataParameter, LayerParameter
    from poseidon_tpu.proto.wire import Datum, encode_datum

    path = str(tmp_path / "float_lmdb")
    w = LMDBWriter(path)
    rs = np.random.RandomState(3)
    for i in range(8):
        arr = rs.rand(2, 6, 6).astype(np.float32)
        w.put(f"{i:08d}".encode(),
              encode_datum(Datum(2, 6, 6, b"", label=i % 3,
                                 float_data=arr.ravel().tolist())))
    w.close()

    lp = LayerParameter(
        name="d", type="DATA", top=["data", "label"],
        data_param=DataParameter(source=path, batch_size=4, backend="LMDB"))
    pipe = BatchPipeline(lp, "TRAIN", 4, device_transform=True)
    assert pipe.device_transform_spec is None
    batch = next(pipe)
    assert batch["data"].dtype == np.float32
    pipe.close()
