"""Native data plane vs Python reference: bit-parity and throughput sanity."""

import numpy as np
import pytest

from poseidon_tpu.data.lmdb_reader import LMDBWriter
from poseidon_tpu.proto.wire import Datum, encode_datum

native = pytest.importorskip("poseidon_tpu.data.native")

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def datum_db(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("db") / "lmdb")
    w = LMDBWriter(path)
    rs = np.random.RandomState(0)
    arrays, labels = [], []
    for i in range(64):
        arr = rs.randint(0, 255, size=(3, 12, 12)).astype(np.uint8)
        label = int(rs.randint(0, 10))
        arrays.append(arr)
        labels.append(label)
        w.put(f"{i:08d}".encode(),
              encode_datum(Datum(3, 12, 12, arr.tobytes(), label=label)))
    w.close()
    return path, arrays, labels


def test_native_reads_match_python(datum_db):
    path, arrays, labels = datum_db
    b = native.NativeLMDBBatcher(path, train=False)
    assert len(b) == 64
    assert b.record_shape == (3, 12, 12)
    data, got_labels = b.batch(np.arange(64))
    for i in range(64):
        np.testing.assert_array_equal(data[i], arrays[i].astype(np.float32))
        assert got_labels[i] == labels[i]
    b.close()


def test_native_transform_matches_python(datum_db):
    path, arrays, labels = datum_db
    mean_values = np.asarray([10.0, 20.0, 30.0], np.float32)
    b = native.NativeLMDBBatcher(path, crop_size=8, train=False, scale=0.5,
                                 mean_values=mean_values)
    data, _ = b.batch(np.asarray([5]))
    # center crop offset (12-8)//2 = 2
    src = arrays[5].astype(np.float32)[:, 2:10, 2:10]
    want = (src - mean_values[:, None, None]) * 0.5
    np.testing.assert_allclose(data[0], want, rtol=1e-6)
    b.close()


def test_native_full_mean_array(datum_db):
    path, arrays, _ = datum_db
    rs = np.random.RandomState(1)
    mean = rs.rand(3, 12, 12).astype(np.float32)
    b = native.NativeLMDBBatcher(path, crop_size=6, train=False, mean=mean)
    data, _ = b.batch(np.asarray([0]))
    src = arrays[0].astype(np.float32)
    off = (12 - 6) // 2
    want = (src - mean)[:, off:off + 6, off:off + 6]
    np.testing.assert_allclose(data[0], want, rtol=1e-5)
    b.close()


def test_native_train_crops_are_valid_windows(datum_db):
    path, arrays, _ = datum_db
    b = native.NativeLMDBBatcher(path, crop_size=8, mirror=True, train=True)
    data, _ = b.batch(np.arange(8), seed=7)
    for i in range(8):
        src = arrays[i].astype(np.float32)
        ok = False
        for ho in range(5):
            for wo in range(5):
                win = src[:, ho:ho + 8, wo:wo + 8]
                if np.allclose(data[i], win) or \
                        np.allclose(data[i], win[:, :, ::-1]):
                    ok = True
        assert ok, f"record {i}: output is not a crop/mirror of the source"
    # determinism: same seed -> same batch
    data2, _ = b.batch(np.arange(8), seed=7)
    np.testing.assert_array_equal(data, data2)
    # different seed -> different crops (with overwhelming probability)
    data3, _ = b.batch(np.arange(8), seed=8)
    assert not np.array_equal(data, data3)
    b.close()


def test_pipeline_uses_native_for_lmdb_data_layer(datum_db):
    path, _, _ = datum_db
    from poseidon_tpu.data.pipeline import BatchPipeline
    from poseidon_tpu.proto.messages import DataParameter, LayerParameter

    lp = LayerParameter(
        name="d", type="DATA", top=["data", "label"],
        data_param=DataParameter(source=path, batch_size=16, backend="LMDB"))
    pipe = BatchPipeline(lp, "TRAIN", 16)
    assert pipe.native is not None, "native path should engage for LMDB DATA"
    batch = next(pipe)
    assert batch["data"].shape == (16, 3, 12, 12)
    assert batch["label"].dtype == np.int32
    pipe.close()

    # forced Python path produces identically-shaped batches
    pipe_py = BatchPipeline(lp, "TRAIN", 16, use_native=False)
    batch_py = next(pipe_py)
    assert batch_py["data"].shape == batch["data"].shape
    pipe_py.close()


def test_native_snappy_matches_python():
    """The C++ decoder (pdp_snappy_uncompress) against the pure-Python codec
    on literals, hand-crafted copy elements, and malformed streams."""
    from poseidon_tpu.data import snappy
    from poseidon_tpu.data.native import available, snappy_uncompress
    if not available():
        import pytest
        pytest.skip("native dataplane not built")
    rs = np.random.RandomState(1)
    for n in [0, 1, 60, 300, 70000]:
        comp = snappy.compress(rs.bytes(n))
        assert snappy_uncompress(comp) == snappy._uncompress_py(comp)
    # copy-1 back-reference incl. overlapping RLE-style copy
    blob = bytes([8]) + bytes([3 << 2]) + b"abcd" + bytes([1, 4])
    assert snappy_uncompress(blob) == b"abcdabcd"
    blob2 = bytes([8]) + bytes([1 << 2]) + b"ab" + bytes([(2 << 2) | 1, 2])
    assert snappy_uncompress(blob2) == b"abababab"
    # copy-2: literal "xy", copy len 3 offset 2 via 2-byte offset
    blob3 = bytes([5]) + bytes([1 << 2]) + b"xy" + \
        bytes([((3 - 1) << 2) | 2, 2, 0])
    assert snappy_uncompress(blob3) == b"xyxyx"
    # malformed: declared length never produced
    import pytest
    with pytest.raises(ValueError):
        snappy_uncompress(bytes([200, 1]) + bytes([3 << 2]) + b"abcd")
