"""Slow, obviously-correct numpy implementations of Caffe layer semantics.

These serve as the golden references for the XLA ops (the role upstream
Caffe's deleted gtest suite played). Written directly from the behavioral
spec in SURVEY.md / the reference sources, as naive loops.
"""

from __future__ import annotations

import math

import numpy as np


def conv_out(h, k, s, p):
    return (h + 2 * p - k) // s + 1


def pool_out(h, k, s, p):
    out = int(math.ceil((h + 2 * p - k) / s)) + 1
    if p > 0 and (out - 1) * s >= h + p:
        out -= 1
    return out


def max_pool(x, k, s, p):
    n, c, h, w = x.shape
    oh, ow = pool_out(h, k, s, p), pool_out(w, k, s, p)
    y = np.full((n, c, oh, ow), -np.inf, np.float32)
    for i in range(n):
        for ch in range(c):
            for ph in range(oh):
                for pw in range(ow):
                    hs, ws = ph * s - p, pw * s - p
                    he, we = min(hs + k, h), min(ws + k, w)
                    hs, ws = max(hs, 0), max(ws, 0)
                    y[i, ch, ph, pw] = x[i, ch, hs:he, ws:we].max()
    return y


def ave_pool(x, k, s, p):
    n, c, h, w = x.shape
    oh, ow = pool_out(h, k, s, p), pool_out(w, k, s, p)
    y = np.zeros((n, c, oh, ow), np.float32)
    for i in range(n):
        for ch in range(c):
            for ph in range(oh):
                for pw in range(ow):
                    hs, ws = ph * s - p, pw * s - p
                    he, we = min(hs + k, h + p), min(ws + k, w + p)
                    pool_size = (he - hs) * (we - ws)
                    hs2, ws2 = max(hs, 0), max(ws, 0)
                    he2, we2 = min(he, h), min(we, w)
                    y[i, ch, ph, pw] = x[i, ch, hs2:he2, ws2:we2].sum() / pool_size
    return y


def lrn_across(x, size, alpha, beta, k=1.0):
    n, c, h, w = x.shape
    pre = (size - 1) // 2
    y = np.zeros_like(x)
    for ch in range(c):
        lo, hi = max(0, ch - pre), min(c, ch - pre + size)
        sq = (x[:, lo:hi] ** 2).sum(axis=1)
        scale = k + alpha / size * sq
        y[:, ch] = x[:, ch] * scale ** (-beta)
    return y


def lrn_within(x, size, alpha, beta):
    pre = (size - 1) // 2
    pooled = ave_pool(x * x, size, 1, pre)
    return x * (1.0 + alpha * pooled) ** (-beta)


def conv2d(x, w, b, stride, pad, group=1):
    n, c, h, wd = x.shape
    o, ig, kh, kw = w.shape
    oh, ow = conv_out(h, kh, stride, pad), conv_out(wd, kw, stride, pad)
    xp = np.zeros((n, c, h + 2 * pad, wd + 2 * pad), np.float32)
    xp[:, :, pad:pad + h, pad:pad + wd] = x
    y = np.zeros((n, o, oh, ow), np.float32)
    og = o // group
    for i in range(n):
        for oc in range(o):
            g = oc // og
            for ph in range(oh):
                for pw in range(ow):
                    patch = xp[i, g * ig:(g + 1) * ig,
                               ph * stride:ph * stride + kh,
                               pw * stride:pw * stride + kw]
                    y[i, oc, ph, pw] = (patch * w[oc]).sum()
            if b is not None:
                y[i, oc] += b[oc]
    return y


def softmax(x, axis=1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def softmax_loss(logits, labels):
    if logits.ndim == 2:
        logits = logits[:, :, None, None]
    n = logits.shape[0]
    sp = logits.shape[2] * logits.shape[3]
    p = softmax(logits, axis=1)
    labels = labels.reshape(n, logits.shape[2], logits.shape[3]).astype(int)
    total = 0.0
    for i in range(n):
        for hh in range(logits.shape[2]):
            for ww in range(logits.shape[3]):
                total -= np.log(max(p[i, labels[i, hh, ww], hh, ww],
                                    np.finfo(np.float32).tiny))
    return total / n / sp
