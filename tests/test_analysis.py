"""Static guardrails (poseidon_tpu/analysis): rule-by-rule fixtures, the
end-to-end run over the real package, and the HLO contract gates.

Layout mirrors the subsystem: (1) synthetic snippets prove each rule
FIRES on a known violation and stays quiet on the lock-disciplined twin;
(2) the whole package is linted against the checked-in baseline — the tree
must ship clean; (3) the checked-in per-model HLO contracts are recomputed
and diffed (the compile half of the gate, same counters CI runs)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from poseidon_tpu.analysis import (Finding, filter_new, load_baseline,
                                   pragma_suppressed, run_lints)
from poseidon_tpu.analysis import contracts as C
from poseidon_tpu.analysis import jit_hygiene, threads

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _thr(src: str):
    return threads.lint_file("synthetic.py", textwrap.dedent(src))


def _jit(src: str, path: str = "synthetic.py"):
    return jit_hygiene.lint_file(path, textwrap.dedent(src))


def _rules(findings):
    return {f.rule for f in findings}


# --------------------------------------------------------------------------- #
# THR: concurrency rules on fixture snippets
# --------------------------------------------------------------------------- #

RACY_COUNTER = """
    import threading

    class Racy:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            while True:
                self.count += 1

        def read(self):
            with self._lock:
                return self.count
"""

LOCKED_TWIN = """
    import threading

    class Disciplined:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            while True:
                with self._lock:
                    self.count += 1

        def read(self):
            with self._lock:
                return self.count
"""


def test_unlocked_counter_flagged_locked_twin_passes():
    racy = _thr(RACY_COUNTER)
    assert "THR004" in _rules(racy), racy
    assert [f.key for f in racy if f.rule == "THR004"] == ["count"]
    assert not _thr(LOCKED_TWIN)


def test_annotated_lock_declaration_recognized():
    """A lock declared with an annotated assignment in __init__ is a lock
    like any other — its regions must credit, not flag."""
    out = _thr("""
        import threading

        class AnnLocked:
            def __init__(self):
                self._lock: threading.Lock = threading.Lock()
                self.count = 0
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                with self._lock:
                    self.count += 1

            def read(self):
                with self._lock:
                    return self.count
    """)
    assert not out, out


def test_acquire_release_region_credits_the_lock():
    """The acquire/try/finally/release idiom holds the lock exactly like
    `with` — and a mutation AFTER the release is still outside it."""
    out = _thr("""
        import threading

        class AcqLocked:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                self._lock.acquire()
                try:
                    self.count += 1
                finally:
                    self._lock.release()

            def read(self):
                with self._lock:
                    return self.count
    """)
    assert not out, out
    out = _thr("""
        import threading

        class PostRelease:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                self._lock.acquire()
                self._lock.release()
                self.count += 1

            def read(self):
                with self._lock:
                    return self.count
    """)
    assert any(f.rule == "THR004" and f.key == "count" for f in out), out


def test_annotated_store_in_thread_body_flagged():
    """`self.count: int = v` in a thread entrypoint stores exactly like
    the plain spelling — an annotation must not hide the race."""
    out = _thr("""
        import threading

        class AnnStore:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                self.count: int = 99

            def read(self):
                with self._lock:
                    return self.count
    """)
    assert any(f.rule == "THR001" and f.key == "count" for f in out), out


def test_unbalanced_acquire_in_with_survives_with_exit():
    """An explicit .acquire() of a DIFFERENT lock inside a `with` body,
    released only after the with exits, keeps its credit across the exit
    — the with-exit pops its OWN lock by name, not the top of the stack."""
    out = _thr("""
        import threading

        class Handoff:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self.count = 0
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                with self._a:
                    self._b.acquire()
                self.count += 1
                self._b.release()

            def read(self):
                with self._b:
                    return self.count
    """)
    assert not out, out


def test_spawn_in_constructor_thread_body_flagged():
    """A thread target defined INSIDE __init__ runs after start() and
    races like any entrypoint; only non-thread init helpers keep the
    publish-before-start exemption."""
    out = _thr("""
        import threading

        class SpawnInCtor:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

                def _loop():
                    while True:
                        self.count += 1

                t = threading.Thread(target=_loop, daemon=True)
                t.start()
    """)
    assert any(f.rule == "THR004" and f.key == "count" for f in out), out


def test_mutation_under_disjoint_locks_flagged():
    """Writers under DIFFERENT locks don't exclude each other — the
    wrong-lock bug is THR006 even though every mutation is locked."""
    out = _thr("""
        import threading

        class WrongLock:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self.n = 0
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                with self._a:
                    self.n += 1

            def bump(self):
                with self._b:
                    self.n += 1
    """)
    assert any(f.rule == "THR006" and f.key == "n" for f in out), out


def test_known_race_flagged_general_mutation():
    """Assign-form (not +=) shared mutation -> THR001."""
    out = _thr("""
        import threading

        class Shared:
            def __init__(self):
                self._lock = threading.Lock()
                self.view = {}
                t = threading.Thread(target=self._poll, daemon=True)
                t.start()

            def _poll(self):
                self.view = {"fresh": True}

            def snapshot(self):
                with self._lock:
                    return dict(self.view)
    """)
    assert "THR001" in _rules(out), out


def test_caller_holds_lock_helper_not_flagged():
    """A private helper mutating state whose EVERY call site holds the
    lock inherits the lock (the _admit_locked pattern)."""
    out = _thr("""
        import threading

        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self.members = set()
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _admit_locked(self, w):
                self.members.add(w)

            def _loop(self):
                with self._lock:
                    self._admit_locked(1)

            def admit(self, w):
                with self._lock:
                    self._admit_locked(w)
    """)
    assert not out, out


def test_lock_order_cycle_detected():
    out = _thr("""
        import threading

        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                with self._a:
                    with self._b:
                        pass

            def other(self):
                with self._b:
                    with self._a:
                        pass
    """)
    cyc = [f for f in out if f.rule == "THR002"]
    assert cyc and "_a" in cyc[0].key and "_b" in cyc[0].key, out


def test_callback_does_not_inherit_registration_site_locks():
    """A method passed AS AN ARGUMENT runs whenever the callee decides,
    not under the locks held where it was registered — the callback edge
    must not feed caller-holds-lock inheritance."""
    out = _thr("""
        import threading

        class Dispatcher:
            def __init__(self):
                self._lock = threading.Lock()
                self.fired = 0
                t = threading.Thread(target=self._drain, daemon=True)
                t.start()

            def _drain(self):
                with self._lock:
                    retry(self._on_event)

            def _on_event(self):
                self.fired += 1
    """)
    assert any(f.rule == "THR004" and f.key == "fired" for f in out), out


def test_lock_order_cycle_detected_in_multi_item_with():
    """`with self._a, self._b:` must record the same _a -> _b order edge
    as the nested spelling."""
    out = _thr("""
        import threading

        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                with self._a, self._b:
                    pass

            def other(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert any(f.rule == "THR002" and "_a" in f.key and "_b" in f.key
               for f in out), out


def test_self_deadlock_on_plain_lock():
    out = _thr("""
        import threading

        class Re:
            def __init__(self):
                self._lock = threading.Lock()
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                with self._lock:
                    self._helper()

            def _helper(self):
                with self._lock:
                    pass
    """)
    assert any(f.rule == "THR002" and f.key == "self:_lock" for f in out), out


def test_rlock_reacquisition_not_flagged():
    """The re-entrant twin of test_self_deadlock_on_plain_lock: RLock
    (and default Condition) re-acquisition is legal and must stay quiet."""
    for ctor in ("RLock", "Condition"):
        out = _thr(f"""
            import threading

            class Re:
                def __init__(self):
                    self._lock = threading.{ctor}()
                    t = threading.Thread(target=self._loop, daemon=True)
                    t.start()

                def _loop(self):
                    with self._lock:
                        self._helper()

                def _helper(self):
                    with self._lock:
                        pass
        """)
        assert not [f for f in out if f.rule == "THR002"], (ctor, out)


def test_check_then_act_flagged():
    out = _thr("""
        import threading

        class CTA:
            def __init__(self):
                self._lock = threading.Lock()
                self.cache = {}
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                if "k" not in self.cache:
                    self.cache["k"] = 1

            def get(self):
                with self._lock:
                    return self.cache.get("k")
    """)
    assert "THR003" in _rules(out), out


def test_check_then_act_inside_init_exempt():
    """__init__ runs before any thread exists (publish-before-start), so
    a check-then-act there must stay quiet — only thread-target locals
    lose the exemption."""
    out = _thr("""
        import threading

        class C:
            def __init__(self, seed):
                self._lock = threading.Lock()
                self.stats = {}
                if seed not in self.stats:
                    self.stats[seed] = 0
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                with self._lock:
                    self.stats["n"] = 1
    """)
    assert not out, out


def test_check_then_act_on_public_attr_without_class_reader_flagged():
    """A PUBLIC attr is readable cross-object (the way server.py reads
    the batcher's counters), so a thread-side check-then-act must fire
    even when no method of the class itself reads it — the cta deferral
    out of THR001/THR004 must not drop it below THR003's bar."""
    out = _thr("""
        import threading

        class Cache:
            def __init__(self):
                self._lock = threading.Lock()
                self.cache = {}
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                if "k" not in self.cache:
                    self.cache["k"] = 1
    """)
    assert "THR003" in _rules(out), out


def test_jax_from_thread_flagged():
    out = _thr("""
        import threading
        import jax

        class BadWorker:
            def __init__(self):
                t = threading.Thread(target=self._loop, daemon=True)
                t.start()

            def _loop(self):
                jax.device_put(1)
    """)
    assert "THR005" in _rules(out), out


def test_mixed_discipline_flagged_without_thread():
    """THR006 needs no Thread construction — a lock-owning class whose
    attr is mutated both under and outside the lock is wrong somewhere."""
    out = _thr("""
        import threading

        class Mixed:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def locked_bump(self):
                with self._lock:
                    self.n += 1

            def unlocked_bump(self):
                self.n += 1
    """)
    assert "THR006" in _rules(out), out


def test_thread_target_nested_function_tracked():
    """The AsyncSnapshotWriter shape: Thread(target=<local fn>)."""
    out = _thr("""
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self.last = None

            def submit(self):
                def _write():
                    self.last = "x"
                t = threading.Thread(target=_write, daemon=True)
                t.start()

            def read(self):
                with self._lock:
                    return self.last
    """)
    assert any(f.symbol == "W.submit._write" for f in out), out


def test_pragma_suppresses_in_place():
    src = textwrap.dedent(RACY_COUNTER).replace(
        "self.count += 1", "self.count += 1  # static-ok: THR004")
    out = [f for f in threads.lint_file("synthetic.py", src)
           if not pragma_suppressed(src.splitlines(), f)]
    assert not out, out


def test_def_level_pragma_suppresses_thr_rules():
    """'# static-ok: RULE' above a def blesses the whole function for
    ANY rule family, as the docs promise — not just the JIT rules."""
    import ast as ast_mod
    src = textwrap.dedent(RACY_COUNTER).replace(
        "    def _loop(self):",
        "    # static-ok: THR004\n    def _loop(self):")
    tree = ast_mod.parse(src)
    out = [f for f in threads.lint_file("synthetic.py", src, tree=tree)
           if not pragma_suppressed(src.splitlines(), f, tree=tree)]
    assert not out, out


# --------------------------------------------------------------------------- #
# JIT: hygiene rules on fixture snippets
# --------------------------------------------------------------------------- #

def test_host_sync_in_traced_function_flagged():
    out = _jit("""
        import jax
        import numpy as np

        def build():
            def step(x):
                y = x + 1
                return np.asarray(y).sum()
            return jax.jit(step)
    """)
    assert any(f.rule == "JIT101" and f.key == "np.asarray" for f in out), out


def test_item_in_decorated_jit_flagged():
    out = _jit("""
        import jax

        @jax.jit
        def step(x):
            return x.item()
    """)
    assert any(f.rule == "JIT101" and f.key == ".item()" for f in out), out


def test_traced_function_resolved_at_depth_and_reported_once():
    """jax.jit over a doubly-nested def resolves to the full qualname,
    and a sync in a nested def of a traced fn lands exactly ONE finding
    (under the innermost def, not doubled via descent)."""
    out = _jit("""
        import jax
        import numpy as np

        class A:
            def b(self):
                def c():
                    def d(x):
                        return np.asarray(x)
                    return jax.jit(d)
                return c
    """)
    hits = [f for f in out if f.rule == "JIT101"]
    assert [f.symbol for f in hits] == ["A.b.c.d"], out
    out = _jit("""
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            def inner(y):
                return np.asarray(y)
            return inner(x)
    """)
    hits = [f for f in out if f.rule == "JIT101"]
    assert [f.symbol for f in hits] == ["step.inner"], out


_PALLAS_KERNEL_SRC = """
    import functools
    import numpy as np
    from jax.experimental import pallas as pl

    def _my_kernel(x_ref, o_ref, *, tile):
        offs = np.asarray(range(tile))        # static index math: fine
        o_ref[...] = x_ref[...] * {payload}

    def run(x):
        return pl.pallas_call(
            functools.partial(_my_kernel, tile=8),
            out_shape=x)(x)
"""


def test_pallas_kernel_body_np_static_math_not_flagged():
    """The carve-out: np.* inside a Pallas kernel body is trace-time
    constant math on static shapes — there is no device value to sync —
    so the host-sync rule must stay quiet there."""
    out = _jit(_PALLAS_KERNEL_SRC.format(payload="offs.sum()"))
    assert not [f for f in out if f.rule == "JIT101"], out


def test_pallas_kernel_body_real_sync_still_fires():
    """.item() (or device_get) inside a kernel body cannot lower at all —
    the kernel-body exemption must NOT blind the rule to it."""
    out = _jit(_PALLAS_KERNEL_SRC.format(payload="x_ref[0].item()"))
    hits = [f for f in out if f.rule == "JIT101" and f.key == ".item()"]
    assert [f.symbol for f in hits] == ["_my_kernel"], out
    assert "Pallas kernel body" in hits[0].message


def test_experimental_tracing_wrapper_still_linted():
    """The jax.experimental import branch (pallas detection) must not
    shadow TRACING_WRAPPERS resolution: a shard_map imported from
    jax.experimental.shard_map still traces its function."""
    out = _jit("""
        from jax.experimental.shard_map import shard_map

        @shard_map
        def step(x):
            return x.item()
    """)
    assert any(f.rule == "JIT101" and f.key == ".item()"
               and f.symbol == "step" for f in out), out


def test_pallas_kernel_detected_through_direct_reference():
    """pallas_call(kernel) without the functools.partial wrapper, via the
    bare-name import form."""
    out = _jit("""
        from jax.experimental.pallas import pallas_call

        def _k(x_ref, o_ref):
            o_ref[...] = x_ref[...].item()

        def run(x):
            return pallas_call(_k, out_shape=x)(x)
    """)
    assert any(f.rule == "JIT101" and f.symbol == "_k"
               and f.key == ".item()" for f in out), out


def test_bound_method_passed_to_jit_is_traced():
    """jax.jit(self._fwd) marks the sibling method traced — the serving
    executor traces its step exactly this way, so a Name-only resolver
    would blind JIT101 to a real in-repo traced function."""
    out = _jit("""
        import jax
        import numpy as np

        class Executor:
            def build(self):
                return jax.jit(self._fwd)

            def _fwd(self, x):
                return np.asarray(x).sum()
    """)
    hits = [f for f in out if f.rule == "JIT101"]
    assert [f.symbol for f in hits] == ["Executor._fwd"], out


def test_host_sync_in_window_flagged():
    """The window table keys on the engine's repo-relative path, so a
    synthetic engine.py exercises the real configuration."""
    out = _jit("""
        class Engine:
            def _dispatch_train_step(self, batch, rng):
                return float(self._helper(batch))

            def _helper(self, batch):
                import jax
                return jax.device_get(batch)
    """, path=os.path.join(REPO, "poseidon_tpu/runtime/engine.py"))
    assert any(f.rule == "JIT102" and f.key == "float()" for f in out), out
    assert any(f.rule == "JIT102" and f.key == "jax.device_get"
               for f in out), out


def test_stale_window_method_surfaces_instead_of_blinding_rule():
    """A WINDOW_METHODS entry that no longer resolves must itself be a
    finding (the JIT105 pattern) — the fixture above defines only
    _dispatch_train_step, so the other configured names must fire."""
    out = _jit("""
        class Engine:
            def _dispatch_train_step(self, batch, rng):
                return batch
    """, path=os.path.join(REPO, "poseidon_tpu/runtime/engine.py"))
    missing = {f.key for f in out
               if f.rule == "JIT102" and f.key.startswith("missing:")}
    assert "missing:Engine._next_batch" in missing, out
    # and the REAL engine resolves every configured name (no findings)
    from poseidon_tpu.analysis import run_lints
    real = run_lints([os.path.join(REPO, "poseidon_tpu/runtime/engine.py")],
                     rules=["JIT102"])
    assert not [f for f in real if f.key.startswith("missing:")], real


def test_retrace_hazard_jit_in_loop():
    out = _jit("""
        import jax

        def bench(xs):
            acc = 0
            for x in xs:
                acc += jax.jit(lambda v: v * 2)(x)
            return acc
    """)
    assert "JIT103" in _rules(out), out
    # stored wrapper outside the loop: deliberate, quiet
    ok = _jit("""
        import jax

        def bench(xs):
            f = jax.jit(lambda v: v * 2)
            return [f(x) for x in xs]
    """)
    assert "JIT103" not in _rules(ok), ok


def test_host_sync_in_control_flow_branch_functions_flagged():
    """fori_loop's body lives at args[2] and cond's false branch at
    args[2] — both trace, so both must be scanned."""
    out = _jit("""
        import jax
        import numpy as np

        def run(x):
            def body(i, acc):
                return acc + np.asarray(i)
            return jax.lax.fori_loop(0, 10, body, x)

        def pick(p, x):
            def t(v):
                return v
            def f(v):
                return np.asarray(v)
            return jax.lax.cond(p, t, f, x)
    """)
    assert {f.symbol for f in out if f.rule == "JIT101"} == \
        {"run.body", "pick.f"}, out


def test_plain_import_jax_numpy_does_not_blind_jax_checks():
    """`import jax.numpy` binds only the root name `jax` — it must not
    remap the 'jax' alias to jnp and hide jax.device_get host syncs."""
    out = _jit("""
        import jax
        import jax.numpy

        @jax.jit
        def step(x):
            return jax.device_get(x)
    """)
    assert any(f.rule == "JIT101" and f.key == "jax.device_get"
               for f in out), out


def test_f64_flagged_under_from_jax_import_numpy():
    out = _jit("""
        from jax import numpy as jnp

        def make():
            return jnp.zeros(3, dtype=jnp.float64)
    """)
    assert any(f.rule == "JIT104" for f in out), out


def test_f64_promotion_flagged():
    out = _jit("""
        import numpy as np

        def bad(x):
            return x.astype("float64") + np.zeros(3, dtype=np.float64)
    """)
    assert sum(1 for f in out if f.rule == "JIT104") == 2, out


def test_named_scope_recognized_as_bare_name_import():
    """`from jax import named_scope` + `with named_scope(...)` keeps the
    JIT105 contract satisfied — the matcher must not require the
    attribute-call spelling."""
    import ast as ast_mod
    names, _dyn = jit_hygiene._named_scope_strings(ast_mod.parse(
        textwrap.dedent("""
            from jax import named_scope

            def update(x):
                with named_scope("optimizer_update"):
                    return x
        """)))
    assert "optimizer_update" in names, names


def test_named_scope_contract_fires_when_scope_removed():
    """updates.py without its optimizer_update scope -> JIT105."""
    path = os.path.join(REPO, "poseidon_tpu/solvers/updates.py")
    out = _jit("def make_update_fn():\n    pass\n", path=path)
    assert any(f.rule == "JIT105" and f.key == "optimizer_update"
               for f in out), out
    # and the real module satisfies its table
    with open(path) as f:
        assert not _jit(f.read(), path=path)


_JIT106_FIXTURE = """
    import jax

    def apply(layers, params, x):
        def _body(p, b):
            {scope_site}
        for layer in layers:
            with jax.named_scope(layer.name):
                pass  # forward-only scope: the recompute escapes it
            x = jax.checkpoint(_body)(params, x)
        return x
"""


def test_jit106_checkpoint_body_without_scope_fires():
    """A checkpointed layer body with the named_scope OUTSIDE it: the ops
    XLA recomputes during backward carry no layer scope, so the remat
    planner's recompute cost would vanish into (unattributed)."""
    out = _jit(_JIT106_FIXTURE.format(scope_site="return b * p"),
               path=os.path.join(REPO, "poseidon_tpu/core/net.py"))
    assert any(f.rule == "JIT106" and f.key == "_body" for f in out), out


def test_jit106_quiet_twin_scope_inside_body():
    """Same fixture with the scope moved INSIDE the checkpointed body —
    quiet; and the rule stays scoped to REMAT_SCOPE_FILES (the identical
    defect in a file outside the table is not its business)."""
    good = _JIT106_FIXTURE.format(
        scope_site='with jax.named_scope("layer"):\n'
                   '                return b * p')
    out = _jit(good, path=os.path.join(REPO, "poseidon_tpu/core/net.py"))
    assert not [f for f in out if f.rule == "JIT106"], out
    elsewhere = _jit(_JIT106_FIXTURE.format(scope_site="return b * p"))
    assert not [f for f in elsewhere if f.rule == "JIT106"], elsewhere


def test_jit106_real_net_module_is_quiet():
    """The shipped core/net.py keeps its named_scope inside the
    checkpointed _body (the wiring the rule exists to protect)."""
    path = os.path.join(REPO, "poseidon_tpu/core/net.py")
    with open(path) as f:
        out = _jit(f.read(), path=path)
    assert not [x for x in out if x.rule == "JIT106"], out


# --------------------------------------------------------------------------- #
# end-to-end: the shipped tree is clean vs the shipped baseline
# --------------------------------------------------------------------------- #

def test_shipped_tree_has_no_new_findings():
    findings = run_lints()
    new = filter_new(findings, load_baseline())
    assert not new, "\n".join(f.render() for f in new)


def test_baseline_entries_still_fire():
    """A baseline entry whose finding no longer exists is stale — shrink
    the file (the grandfather list must never outlive its findings)."""
    live = {f.fingerprint for f in run_lints()}
    stale = [fp for fp in load_baseline() if fp not in live]
    assert not stale, f"stale baseline entries (delete them): {stale}"


def test_cli_exit_codes(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO)
    clean = subprocess.run(
        [sys.executable, "-m", "poseidon_tpu.analysis"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    racy = tmp_path / "racy.py"
    racy.write_text(textwrap.dedent(RACY_COUNTER))
    report = tmp_path / "report.json"
    dirty = subprocess.run(
        [sys.executable, "-m", "poseidon_tpu.analysis", str(racy),
         "--report", str(report)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert dirty.returncode == 1, dirty.stdout + dirty.stderr
    assert "THR004" in dirty.stdout
    doc = json.loads(report.read_text())
    assert doc["new"] == 1 and doc["findings"]

    # usage errors exit 3 — NOT 2, which means a real contract violation
    typo = subprocess.run(
        [sys.executable, "-m", "poseidon_tpu.analysis",
         "--contracts", "lenett"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
    assert typo.returncode == 3, typo.stdout + typo.stderr
    assert "unknown model" in typo.stderr


def test_cli_no_fail_on_new_is_report_only(tmp_path):
    """--no-fail-on-new surveys findings without failing (e.g. from a
    pre-commit hook while triaging) — same output, exit 0."""
    from poseidon_tpu.analysis import __main__ as M
    racy = tmp_path / "racy.py"
    racy.write_text(textwrap.dedent(RACY_COUNTER))
    assert M.main([str(racy)]) == 1                       # default fails
    assert M.main(["--no-fail-on-new", str(racy)]) == 0


def test_cli_rejects_nonexistent_target_and_bad_flag_with_exit_3():
    """A typo'd path or flag must never read as '0 findings, clean' —
    and must not collide with exit 2 (contract violation) either."""
    env = dict(os.environ, PYTHONPATH=REPO)
    for argv in (["no_such_file.py"], ["--bogus"]):
        r = subprocess.run(
            [sys.executable, "-m", "poseidon_tpu.analysis"] + argv,
            cwd=REPO, env=env, capture_output=True, text=True, timeout=120)
        assert r.returncode == 3, (argv, r.stdout, r.stderr)


def test_cli_empty_contract_spec_is_a_usage_error():
    """--contracts ',' (or '' from an unset CI variable) must not run a
    gate over zero models and read as passing — exit 3 like any typo."""
    from poseidon_tpu.analysis import __main__ as M
    for spec in (",", ""):
        with pytest.raises(SystemExit) as e:
            M.main(["--contracts", spec])
        assert e.value.code == 3, spec


def test_missing_configured_script_target_surfaces_cfg001():
    """EXTRA_SCRIPT_TARGETS rot must surface as a finding, not silently
    shrink lint coverage (the WINDOW_METHODS pattern)."""
    from poseidon_tpu import analysis as A
    out = A.run_lints([os.path.join(A.REPO_ROOT, "scripts/gone.py")])
    assert [f.rule for f in out] == ["CFG001"], out
    # a --rules-restricted run (pre-commit hook style) must not filter
    # the infrastructure finding away and read as clean coverage
    out = A.run_lints([os.path.join(A.REPO_ROOT, "scripts/gone.py")],
                      rules=["THR001", "THR004"])
    assert [f.rule for f in out] == ["CFG001"], out


def test_cli_contract_infra_failure_exits_4_and_keeps_report(
        tmp_path, monkeypatch):
    """A crash while MEASURING contracts is exit 4 (not a lint 1 or a
    violation 2) and the already-complete lint report still lands."""
    from poseidon_tpu.analysis import __main__ as M
    from poseidon_tpu.analysis import contracts as C

    def boom(models):
        raise RuntimeError("simulated infra failure")

    monkeypatch.setattr(C, "check_all", boom)
    report = tmp_path / "r.json"
    rc = M.main(["--contracts", "lenet", "--report", str(report)])
    assert rc == 4
    doc = json.loads(report.read_text())
    assert "simulated infra failure" in doc["contracts_error"]


# --------------------------------------------------------------------------- #
# HLO contract gates
# --------------------------------------------------------------------------- #

def test_contract_diff_detects_synthetic_violation():
    """Pure-diff half: a regressed counter or lost donation is reported
    without any compilation."""
    golden = C.load_contract("googlenet")
    assert golden is not None, "missing checked-in googlenet contract"
    fresh = json.loads(json.dumps(golden))
    fresh["stablehlo"]["gradient_all_reduces"] = 120   # per-leaf regression
    diffs = C.diff_contracts(golden, fresh)
    assert diffs and "gradient_all_reduces" in diffs[0], diffs
    fresh = json.loads(json.dumps(golden))
    fresh["stablehlo"]["donated_buffers"] = 0
    don = [d for d in C.diff_contracts(golden, fresh) if "donat" in d]
    assert len(don) == 1, don      # one defect, one line — never doubled
    # across a jax version the exact compare is skipped but the
    # non-emptiness claim still holds the line
    fresh["generated_with"]["jax"] = "999.0.0"
    assert any("donates nothing" in d
               for d in C.diff_contracts(golden, fresh))
    assert not C.diff_contracts(golden, golden)


def test_contract_device_count_mismatch_refuses_not_violates():
    """A golden measured on a different device count is NOT comparable:
    check_model refuses (ContractEnvironmentError -> CLI exit 4), never
    reporting the mismatch as a violation (exit 2)."""
    golden = C.load_contract("lenet")
    fresh = json.loads(json.dumps(golden))
    fresh["generated_with"]["n_devices"] = 1
    with pytest.raises(C.ContractEnvironmentError, match="not comparable"):
        C.check_model("lenet", fresh=fresh)


def test_contract_robust_subset_exempts_optimized_section():
    """Under jax version drift the optimized-HLO counters (compiler
    output) are skipped, while program-level stablehlo counters stay
    exact-compared."""
    golden = C.load_contract("lenet")
    assert golden is not None and "optimized" in golden
    fresh = json.loads(json.dumps(golden))
    fresh["generated_with"]["jax"] = "999.0.0"
    fresh["optimized"]["layout_transposes"] += 7
    fresh["optimized"]["fusion_count"] += 3
    assert not any("optimized" in d
                   for d in C.diff_contracts(golden, fresh))
    fresh["stablehlo"]["gradient_all_reduces"] += 1
    assert any("gradient_all_reduces" in d
               for d in C.diff_contracts(golden, fresh))


def test_hlo_contract_lenet():
    """Fast lane: LeNet traces + CPU-compiles in seconds, so the full
    gate (stablehlo AND optimized sections) runs in every tier-1 sweep."""
    ok, diffs = C.check_model("lenet")
    assert ok, diffs


def test_contract_headline_numbers_are_pinned():
    """The golden FILES themselves carry the marquee invariants — a
    hand-edit that waters them down fails here without any compile."""
    alexnet = C.load_contract("alexnet")
    assert alexnet["nhwc"]["layout_transposes"] == 2      # fc6 pair only
    googlenet = C.load_contract("googlenet")
    assert googlenet["stablehlo"]["gradient_all_reduces"] == \
        googlenet["config"]["arena_buckets"] == 11         # never ~120
    for m in C.MODELS:
        c = C.load_contract(m)
        assert c["stablehlo"]["f64_tensors"] == 0
        assert c["stablehlo"]["donated_buffers"] > 0
        assert c["generated_with"]["n_devices"] == 8


@pytest.mark.slow
def test_hlo_contract_alexnet():
    """Slow lane (~35s of tracing incl. the NHWC re-trace at 227 px):
    the tier-1 870s sweep budget can't afford it, so CI verifies it on
    every push via `scripts/check_static.py --contracts all` instead
    (the dedicated static-analysis step in tier1.yml)."""
    ok, diffs = C.check_model("alexnet")
    assert ok, diffs


@pytest.mark.slow
def test_hlo_contract_googlenet():
    """Slow lane (~25s of tracing); CI covers it via check_static
    --contracts all, same as alexnet."""
    ok, diffs = C.check_model("googlenet")
    assert ok, diffs


# --------------------------------------------------------------------------- #
# conftest thread sanitizer
# --------------------------------------------------------------------------- #

@pytest.mark.allow_thread_exceptions
def test_thread_excepthook_records():
    """The sanitizer's hook sees uncaught thread exceptions (this test
    carries the marker, so recording one must NOT fail it)."""
    import threading

    # the hook's globals ARE the conftest module namespace (tests/ is not
    # a package, so the module isn't importable by a stable name)
    _THREAD_ERRORS = threading.excepthook.__globals__["_THREAD_ERRORS"]
    n0 = len(_THREAD_ERRORS)

    def boom():
        raise RuntimeError("intentional sanitizer probe")

    t = threading.Thread(target=boom, daemon=True)
    t.start()
    t.join(2.0)
    assert len(_THREAD_ERRORS) == n0 + 1
    thread, msg = _THREAD_ERRORS[-1]
    # the OBJECT is recorded (idents get recycled across thread lifetimes)
    assert thread is t
    assert "intentional sanitizer probe" in msg
