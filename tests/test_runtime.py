"""End-to-end runtime tests: Engine driven by real prototxt files, CLI tools."""

import os

import numpy as np
import pytest

N_DEV = 8


def _write_mnistish_prototxt(tmp_path, batch=8, max_iter=30):
    """MEMORY_DATA-driven LeNet-small net + solver, as files."""
    net = tmp_path / "net.prototxt"
    net.write_text("""
name: "SmallNet"
layers {
  name: "mnist" type: MEMORY_DATA top: "data" top: "label"
  memory_data_param { batch_size: %d channels: 1 height: 12 width: 12 }
}
layers {
  name: "conv1" type: CONVOLUTION bottom: "data" top: "conv1"
  blobs_lr: 1 blobs_lr: 2
  convolution_param { num_output: 8 kernel_size: 3
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "relu1" type: RELU bottom: "conv1" top: "conv1" }
layers { name: "pool1" type: POOLING bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layers {
  name: "ip1" type: INNER_PRODUCT bottom: "pool1" top: "ip1"
  inner_product_param { num_output: 5
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "loss" type: SOFTMAX_LOSS bottom: "ip1" bottom: "label" top: "loss" }
layers { name: "acc" type: ACCURACY bottom: "ip1" bottom: "label" top: "accuracy"
  include { phase: TEST } }
""" % batch)
    solver = tmp_path / "solver.prototxt"
    solver.write_text(f"""
net: "{net}"
base_lr: 0.05
lr_policy: "fixed"
momentum: 0.9
weight_decay: 0.0005
display: 10
max_iter: {max_iter}
test_iter: 4
test_interval: 15
test_initialization: false
snapshot: 0
snapshot_prefix: "snap/smallnet"
random_seed: 3
""")
    return str(solver)


def _memory_data(n=256, seed=0):
    rs = np.random.RandomState(seed)
    templates = rs.randn(5, 1, 12, 12).astype(np.float32)
    labels = rs.randint(0, 5, size=n)
    data = templates[labels] + 0.25 * rs.randn(n, 1, 12, 12).astype(np.float32)
    return {"data": data, "label": labels}


def test_engine_end_to_end(tmp_path):
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    solver_path = _write_mnistish_prototxt(tmp_path)
    sp = load_solver(solver_path)
    eng = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path))
    try:
        first_loss = None
        last = eng.train()
        assert last["loss"] < 0.3, f"did not converge: {last}"
        # test-phase metrics exist and are good on the easy task
        out = eng.test(0)
        assert out["accuracy"] > 0.9
        # artifacts
        assert (tmp_path / "SmallNet_train_outputs.csv").exists()
        assert (tmp_path / "stats.yaml").exists()
    finally:
        eng.close()


def test_engine_snapshot_restore(tmp_path):
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    solver_path = _write_mnistish_prototxt(tmp_path, max_iter=10)
    sp = load_solver(solver_path)
    sp.snapshot_after_train = True
    eng = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path))
    try:
        eng.train()
        state_path = str(tmp_path / "snap" / "smallnet_iter_10.solverstate.npz")
        model_path = str(tmp_path / "snap" / "smallnet_iter_10.caffemodel")
        assert os.path.exists(state_path) and os.path.exists(model_path)
    finally:
        eng.close()

    # resume: a fresh engine restored at iter 10 continues to 20
    eng2 = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path))
    try:
        eng2.restore_from(state_path)
        assert int(eng2.state.solver.it) == 10
        eng2.train(max_iter=20)
        assert int(eng2.state.solver.it) == 20
    finally:
        eng2.close()

    # .caffemodel weights load back bit-exact
    from poseidon_tpu.runtime.checkpoint import load_caffemodel, restore
    params_snap, _ = restore(state_path)
    eng3 = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path))
    try:
        loaded = load_caffemodel(model_path, eng3.train_net, eng3.params)
        for l, lp in params_snap.items():
            for k in lp:
                np.testing.assert_allclose(np.asarray(loaded[l][k]),
                                           np.asarray(lp[k]), rtol=1e-6)
    finally:
        eng3.close()


def test_arena_snapshot_portability(tmp_path):
    """Snapshots are canonical per-leaf under the flat parameter arena: a
    per-leaf snapshot written before the arena existed loads into an
    arena-backed run, trains, re-snapshots, and that snapshot reloads with
    --param_arena=false bit-identically — the same training continuation
    either way (params, momentum history, iteration)."""
    from poseidon_tpu.parallel import CommConfig
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.checkpoint import restore
    from poseidon_tpu.runtime.engine import Engine

    solver_path = _write_mnistish_prototxt(tmp_path, max_iter=6)

    def run(arena: bool, outdir: str, resume=None, to_iter=6):
        sp = load_solver(solver_path)
        sp.snapshot_after_train = True
        eng = Engine(sp, comm=CommConfig(param_arena=arena),
                     memory_data=_memory_data(), output_dir=outdir)
        try:
            assert (eng.train_step.arena is not None) == arena
            if resume:
                eng.restore_from(resume)
            eng.train(max_iter=to_iter)
        finally:
            eng.close()
        return os.path.join(outdir, "snap",
                            f"smallnet_iter_{to_iter}.solverstate.npz")

    # 1) the "pre-arena" snapshot: a per-leaf run to iter 6
    base = run(False, str(tmp_path / "leaf"))
    assert os.path.exists(base)
    # 2) continue 6 -> 9 under the arena, and per-leaf as the reference
    snap_arena = run(True, str(tmp_path / "arena9"), resume=base, to_iter=9)
    snap_leaf = run(False, str(tmp_path / "leaf9"), resume=base, to_iter=9)
    pa, sa = restore(snap_arena)
    pl, sl = restore(snap_leaf)
    assert int(sa.solver.it) == int(sl.solver.it) == 9
    for l in pa:
        for k in pa[l]:
            np.testing.assert_array_equal(
                np.asarray(pa[l][k]), np.asarray(pl[l][k]),
                err_msg=f"params {l}/{k}")
            np.testing.assert_array_equal(
                np.asarray(sa.solver.history[l][k]),
                np.asarray(sl.solver.history[l][k]),
                err_msg=f"history {l}/{k}")
    # 3) the arena run's snapshot reloads into a per-leaf run and trains —
    # continuation parity 9 -> 12 across the representation boundary
    snap_a12 = run(False, str(tmp_path / "a12"), resume=snap_arena,
                   to_iter=12)
    snap_l12 = run(True, str(tmp_path / "l12"), resume=snap_leaf,
                   to_iter=12)
    pa12, _ = restore(snap_a12)
    pl12, _ = restore(snap_l12)
    for l in pa12:
        for k in pa12[l]:
            np.testing.assert_array_equal(
                np.asarray(pa12[l][k]), np.asarray(pl12[l][k]),
                err_msg=f"12 {l}/{k}")


def test_stale_snapshot_tmp_swept_and_never_shadows(tmp_path):
    """Crash-safe snapshot hygiene: a process killed between tmp-write and
    os.replace leaves ``*_iter_N.*.tmp.<pid>`` litter. The sweep removes
    tmps whose writer pid is dead, leaves a live sibling's in place, and
    a truncated tmp is NEVER selected by latest_snapshot (the atomic-
    rename contract: only completed artifacts carry the real suffix)."""
    import subprocess
    import sys

    from poseidon_tpu.runtime.checkpoint import (latest_snapshot,
                                                 sweep_stale_tmp)

    snap_dir = tmp_path / "snap"
    snap_dir.mkdir()
    prefix = str(snap_dir / "net")
    good = snap_dir / "net_iter_10.solverstate.npz"
    np.savez(str(good), iter=np.asarray(10))

    # a dead writer's truncated tmp at a LATER iteration
    p = subprocess.run([sys.executable, "-c", "import os; print(os.getpid())"],
                       capture_output=True, check=True)
    dead_pid = int(p.stdout)
    stale = snap_dir / f"net_iter_20.solverstate.npz.tmp.{dead_pid}"
    stale.write_bytes(b"half-written garbage")
    old = os.path.getmtime(stale) - 120
    os.utime(stale, (old, old))     # past the shared-fs age guard
    # a LIVE sibling writer's in-flight tmp (this process's pid stands in
    # for a concurrent rank mid-snapshot... except sweep treats its OWN
    # pid as stale — so use a real live other process: our parent
    live_pid = os.getppid()
    live = snap_dir / f"net_iter_30.solverstate.npz.tmp.{live_pid}"
    live.write_bytes(b"in flight")
    # a dead-pid tmp too FRESH for the age guard: could be a live writer
    # on another host (the pid test is host-local) — must survive
    fresh = snap_dir / f"net_iter_40.solverstate.npz.tmp.{dead_pid}"
    fresh.write_bytes(b"maybe another host")

    # the truncated tmp never shadows the good checkpoint
    assert latest_snapshot(prefix) == str(good)

    removed = sweep_stale_tmp(prefix)
    assert [os.path.basename(r) for r in removed] == [stale.name]
    assert not stale.exists()
    assert live.exists()            # live writer untouched
    assert fresh.exists()           # inside the age guard: untouched
    assert good.exists()            # completed artifact untouched
    assert latest_snapshot(prefix) == str(good)
    live.unlink()
    fresh.unlink()


def test_engine_auto_resume(tmp_path):
    """Restart-after-preemption: the relaunched engine sweeps a dead
    predecessor's tmp litter, restores the newest solverstate under the
    solver's snapshot prefix, and continues training from there."""
    import subprocess
    import sys

    import pytest

    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    solver_path = _write_mnistish_prototxt(tmp_path, max_iter=10)
    sp = load_solver(solver_path)
    sp.snapshot_after_train = True
    try:
        eng = Engine(sp, memory_data=_memory_data(),
                     output_dir=str(tmp_path))
    except AttributeError as e:
        # same environment gap that fails every Engine-constructing test
        # in this suite (jax.shard_map absent on this jax build)
        pytest.skip(f"Engine unavailable here: {e}")
    try:
        eng.train()
    finally:
        eng.close()
    state_path = tmp_path / "snap" / "smallnet_iter_10.solverstate.npz"
    assert state_path.exists()
    # the "killed mid-snapshot" predecessor's litter
    p = subprocess.run([sys.executable, "-c", "import os; print(os.getpid())"],
                       capture_output=True, check=True)
    dead_pid = int(p.stdout)
    litter = tmp_path / "snap" / \
        f"smallnet_iter_15.solverstate.npz.tmp.{dead_pid}"
    litter.write_bytes(b"truncated")
    old = os.path.getmtime(litter) - 120
    os.utime(litter, (old, old))    # past the shared-fs age guard

    eng2 = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path))
    try:
        restored = eng2.auto_resume()
        assert restored == str(state_path)
        assert not litter.exists()              # swept on resume
        assert int(eng2.state.solver.it) == 10
        eng2.train(max_iter=16)
        assert int(eng2.state.solver.it) == 16
    finally:
        eng2.close()

    # nothing to resume from -> fresh start, explicit None
    empty = tmp_path / "fresh"
    empty.mkdir()
    eng3 = Engine(sp, memory_data=_memory_data(), output_dir=str(empty))
    try:
        assert eng3.auto_resume() is None
    finally:
        eng3.close()


def test_engine_ssp_end_to_end(tmp_path):
    """--staleness as a product feature: Engine trains under SSP, converges,
    snapshots SSPState, and a fresh SSP engine resumes from it exactly."""
    from poseidon_tpu.parallel.trainer import SSPState
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    solver_path = _write_mnistish_prototxt(tmp_path, max_iter=30)
    sp = load_solver(solver_path)
    sp.snapshot_after_train = True
    eng = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path),
                 staleness=2)
    try:
        last = eng.train()
        assert last["loss"] < 0.4, f"SSP did not converge: {last}"
        assert isinstance(eng.state, SSPState)
        assert eng.iteration() == 30
        out = eng.test(0)  # eval runs off the synced anchor view
        assert out["accuracy"] > 0.85
    finally:
        eng.close()

    state_path = str(tmp_path / "snap" / "smallnet_iter_30.solverstate.npz")
    assert os.path.exists(state_path)

    # SSP-state roundtrip: restored local replicas + anchor are bit-exact
    eng2 = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path),
                  staleness=2)
    try:
        eng2.restore_from(state_path)
        assert eng2.iteration() == 30
        for l, lp in eng.state.local_params.items():
            for k in lp:
                np.testing.assert_array_equal(
                    np.asarray(eng2.state.local_params[l][k]),
                    np.asarray(lp[k]), err_msg=f"{l}/{k}")
        eng2.train(max_iter=36)
        assert eng2.iteration() == 36
    finally:
        eng2.close()

    # cross-mode restore: a dense engine adopts the SSP anchor view
    eng3 = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path))
    try:
        eng3.restore_from(state_path)
        assert eng3.iteration() == 30
        for l, lp in eng.state.anchor_params.items():
            for k in lp:
                np.testing.assert_array_equal(
                    np.asarray(eng3.params[l][k]), np.asarray(lp[k]))
    finally:
        eng3.close()


def test_debug_info_prints_layer_stats(tmp_path, capsys):
    """solver debug_info: per-layer blob/param/grad magnitudes at display
    boundaries (net.cpp ForwardDebugInfo/UpdateDebugInfo analog)."""
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    solver_path = _write_mnistish_prototxt(tmp_path, max_iter=10)
    sp = load_solver(solver_path)
    sp.debug_info = True
    eng = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path))
    try:
        eng.train()
    finally:
        eng.close()
    out = capsys.readouterr().out
    assert "[debug] blob  conv1:" in out
    assert "[debug] param conv1/w:" in out
    assert "[debug] grad  conv1/w:" in out
    # magnitudes are real numbers, not zeros across the board
    import re
    vals = [float(m) for m in re.findall(r"\[debug\] \S+\s+\S+: ([\d.e+-]+)",
                                         out)]
    assert any(v > 0 for v in vals)


def test_cli_staleness_flag():
    from poseidon_tpu.runtime.cli import build_parser
    args = build_parser().parse_args(
        ["train", "--solver", "x.prototxt", "--staleness", "3"])
    assert args.staleness == 3


def test_cli_device_query(capsys):
    from poseidon_tpu.runtime.cli import main
    assert main(["device_query"]) == 0
    out = capsys.readouterr().out
    assert "device 0" in out and f"local_devices={N_DEV}" in out


def test_cli_time_deploy_net(tmp_path, capsys):
    model = tmp_path / "deploy.prototxt"
    model.write_text("""
name: "tiny"
input: "data"
input_dim: 4 input_dim: 3 input_dim: 8 input_dim: 8
layers { name: "conv" type: CONVOLUTION bottom: "data" top: "conv"
  convolution_param { num_output: 4 kernel_size: 3
    weight_filler { type: "xavier" } } }
layers { name: "fc" type: INNER_PRODUCT bottom: "conv" top: "fc"
  inner_product_param { num_output: 2 weight_filler { type: "xavier" } } }
layers { name: "silence" type: SILENCE bottom: "fc" }
""")
    from poseidon_tpu.runtime.cli import main
    assert main(["time", "--model", str(model), "--iterations", "3",
                 "--batch_size", "4"]) == 0
    out = capsys.readouterr().out
    assert "Average Forward pass" in out
    assert "Average Forward-Backward" in out


def test_cli_dataset_tools_roundtrip(tmp_path, capsys):
    from PIL import Image
    from poseidon_tpu.runtime.cli import main

    rs = np.random.RandomState(0)
    lines = []
    for i in range(6):
        img = Image.fromarray(rs.randint(0, 255, (9, 9, 3)).astype(np.uint8))
        p = tmp_path / f"i{i}.png"
        img.save(p)
        lines.append(f"{p} {i % 2}")
    listfile = tmp_path / "list.txt"
    listfile.write_text("\n".join(lines))
    db = str(tmp_path / "db")

    assert main(["convert_imageset", str(listfile), db,
                 "--resize_height", "8", "--resize_width", "8"]) == 0
    mean_file = str(tmp_path / "mean.binaryproto")
    assert main(["compute_image_mean", db, mean_file]) == 0
    assert main(["partition_data", db, "3"]) == 0

    from poseidon_tpu.data.sources import LMDBSource
    src = LMDBSource(db)
    assert len(src) == 6
    arr, label = src.read(0)
    assert arr.shape == (3, 8, 8)
    shard_sizes = [len(LMDBSource(f"{db}_{s}")) for s in range(3)]
    assert shard_sizes == [2, 2, 2]

    from poseidon_tpu.proto.wire import read_blob_file
    mean = read_blob_file(mean_file)
    assert mean.shape == (1, 3, 8, 8)


def test_extract_features(tmp_path):
    from poseidon_tpu.core.net import Net
    from poseidon_tpu.data.pipeline import BatchPipeline
    from poseidon_tpu.proto.messages import load_net_from_string
    from poseidon_tpu.runtime.tools import extract_features
    import jax

    net_param = load_net_from_string("""
    name: "feat"
    layers { name: "src" type: MEMORY_DATA top: "data" top: "label"
      memory_data_param { batch_size: 4 channels: 1 height: 6 width: 6 } }
    layers { name: "ip" type: INNER_PRODUCT bottom: "data" top: "feat"
      inner_product_param { num_output: 7 weight_filler { type: "xavier" } } }
    layers { name: "s" type: SILENCE bottom: "feat" }
    layers { name: "s2" type: SILENCE bottom: "label" }
    """)
    md = {"data": np.random.RandomState(0).rand(16, 1, 6, 6).astype(np.float32),
          "label": np.arange(16) % 2}
    lp = net_param.layers[0]
    pipe = BatchPipeline(lp, "TEST", 4, memory_data=md)
    net = Net(net_param, "TEST",
              source_shapes={"data": (4, 1, 6, 6), "label": (4,)})
    params = net.init(jax.random.PRNGKey(0))
    out = extract_features(net, params, ["feat"], pipe, 3,
                           str(tmp_path / "features"))
    pipe.close()

    from poseidon_tpu.data.lmdb_reader import LMDBReader
    from poseidon_tpu.proto.wire import decode_datum
    r = LMDBReader(out[0])
    assert len(r) == 12
    d = decode_datum(r.value_at(0))
    assert d.channels == 7
    assert d.float_data is not None and len(d.float_data) == 7


def test_hdf5_output_layer_dumps(tmp_path):
    import h5py
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    net = tmp_path / "net.prototxt"
    net.write_text("""
name: "H5Net"
layers {
  name: "src" type: MEMORY_DATA top: "data" top: "label"
  memory_data_param { batch_size: 4 channels: 1 height: 6 width: 6 }
}
layers { name: "ip" type: INNER_PRODUCT bottom: "data" top: "feat"
  inner_product_param { num_output: 3 weight_filler { type: "xavier" } } }
layers { name: "loss" type: SOFTMAX_LOSS bottom: "feat" bottom: "label" top: "loss" }
layers { name: "dump" type: HDF5_OUTPUT bottom: "feat"
  include { phase: TEST }
  hdf5_output_param { file_name: "features.h5" } }
""")
    solver = tmp_path / "solver.prototxt"
    solver.write_text(f"""
net: "{net}"
base_lr: 0.01
lr_policy: "fixed"
max_iter: 2
test_iter: 3
test_interval: 2
test_initialization: false
""")
    md = {"data": np.random.RandomState(0).rand(64, 1, 6, 6).astype(np.float32),
          "label": np.arange(64) % 3}
    eng = Engine(load_solver(str(solver)), memory_data=md,
                 output_dir=str(tmp_path))
    try:
        eng.train()
    finally:
        eng.close()
    with h5py.File(tmp_path / "features.h5", "r") as f:
        feats = np.asarray(f["feat"])
    assert feats.shape == (3 * 4 * N_DEV, 3)  # test_iter * global batch


def test_hdf5_output_during_train(tmp_path):
    """HDF5_OUTPUT in the TRAIN phase (round-1 gap): after training, the
    file holds the LAST batch's bottoms — the reference's
    overwrite-per-forward semantics (hdf5_output_layer.cpp)."""
    import h5py
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    net = tmp_path / "net.prototxt"
    net.write_text("""
name: "H5Train"
layers {
  name: "src" type: MEMORY_DATA top: "data" top: "label"
  memory_data_param { batch_size: 4 channels: 1 height: 6 width: 6 }
}
layers { name: "ip" type: INNER_PRODUCT bottom: "data" top: "feat"
  inner_product_param { num_output: 3 weight_filler { type: "xavier" } } }
layers { name: "loss" type: SOFTMAX_LOSS bottom: "feat" bottom: "label" top: "loss" }
layers { name: "dump" type: HDF5_OUTPUT bottom: "feat" bottom: "label"
  include { phase: TRAIN }
  hdf5_output_param { file_name: "train_feats.h5" } }
""")
    solver = tmp_path / "solver.prototxt"
    solver.write_text(f"""
net: "{net}"
base_lr: 0.01
lr_policy: "fixed"
max_iter: 3
""")
    md = {"data": np.random.RandomState(0).rand(64, 1, 6, 6).astype(np.float32),
          "label": np.arange(64) % 3}
    eng = Engine(load_solver(str(solver)), memory_data=md,
                 output_dir=str(tmp_path))
    try:
        eng.train()
    finally:
        eng.close()
    with h5py.File(tmp_path / "train_feats.h5", "r") as f:
        feats = np.asarray(f["feat"])
        labels = np.asarray(f["label"])
    # one (latest) global batch, not an accumulation across iterations
    assert feats.shape == (4 * N_DEV, 3)
    assert labels.shape == (4 * N_DEV,)


def test_engine_steps_per_dispatch(tmp_path):
    """Chunked dispatch (K steps per compiled program) trains like the
    single-step engine and keeps exact display/test cadence: same number
    of metric rows, convergence, boundary alignment (max_iter=30 with
    display=10, test_interval=15, K=4 forces chunk fallbacks at 8->10,
    12->15, 28->30)."""
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    solver_path = _write_mnistish_prototxt(tmp_path)
    sp = load_solver(solver_path)
    eng = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path),
                 steps_per_dispatch=4)
    try:
        assert eng._scan_step is not None
        last = eng.train()
        assert last["loss"] < 0.3, f"did not converge: {last}"
        out = eng.test(0)
        assert out["accuracy"] > 0.9
        # every optimizer step must have produced a metrics row
        csv = (tmp_path / "SmallNet_train_outputs.csv").read_text()
        data_rows = [ln for ln in csv.strip().splitlines()[1:] if ln]
        # rows flush per display window (3 windows of 10 at max_iter 30)
        assert len(data_rows) == 3, csv
        assert eng.iteration() == sp.max_iter
    finally:
        eng.close()


def test_engine_iter_size(tmp_path):
    """iter_size (gradient accumulation, V2 surface) through the full
    Engine: converges, and the TEST path still places its (non-stacked)
    batches correctly — the eval-batch sharding regression a CLI drive
    caught (train_step.batch_sharding gains a leading [iter_size] axis the
    test batches must not inherit)."""
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    solver_path = _write_mnistish_prototxt(tmp_path)
    sp = load_solver(solver_path)
    sp.iter_size = 2
    eng = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path))
    try:
        assert eng.iter_size == 2
        last = eng.train()  # test_interval=15 exercises eval mid-train
        assert last["loss"] < 0.3, f"did not converge: {last}"
        out = eng.test(0)
        assert out["accuracy"] > 0.9
    finally:
        eng.close()


def test_engine_iter_size_composes_with_chunking(tmp_path):
    """iter_size x steps_per_dispatch: batches stack [chunk, iter, B, ...]
    and the cadence bookkeeping still lands exactly on max_iter."""
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    solver_path = _write_mnistish_prototxt(tmp_path)
    sp = load_solver(solver_path)
    sp.iter_size = 2
    eng = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path),
                 steps_per_dispatch=4)
    try:
        assert eng._scan_step is not None and eng.iter_size == 2
        last = eng.train()
        assert last["loss"] < 0.3, f"did not converge: {last}"
        assert eng.iteration() == sp.max_iter
    finally:
        eng.close()


def test_engine_steps_per_dispatch_ssp_falls_back(tmp_path):
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    solver_path = _write_mnistish_prototxt(tmp_path, max_iter=6)
    sp = load_solver(solver_path)
    eng = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path),
                 staleness=1, steps_per_dispatch=4)
    try:
        assert eng._scan_step is None and eng.steps_per_dispatch == 1
    finally:
        eng.close()


def test_engine_device_transform_matches_host_path(tmp_path):
    """--device_transform (uint8 ingest + on-device (x-mean)*scale) must
    train IDENTICALLY to the host-transform path: same pipeline seed picks
    the same crops/mirrors, and the normalization arithmetic is the same
    f32 math on either side of the transfer."""
    import jax
    from poseidon_tpu.data.lmdb_reader import LMDBWriter
    from poseidon_tpu.proto.wire import Datum, encode_datum
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    db = str(tmp_path / "train_lmdb")
    w = LMDBWriter(db)
    rs = np.random.RandomState(0)
    templates = rs.randint(40, 215, size=(5, 1, 12, 12))
    for i in range(128):
        label = int(rs.randint(0, 5))
        arr = np.clip(templates[label]
                      + rs.randint(-25, 25, size=(1, 12, 12)), 0, 255)
        w.put(f"{i:08d}".encode(),
              encode_datum(Datum(1, 12, 12,
                                 arr.astype(np.uint8).tobytes(),
                                 label=label)))
    w.close()

    net = tmp_path / "net.prototxt"
    net.write_text("""
name: "U8Net"
layers {
  name: "d" type: DATA top: "data" top: "label"
  data_param { source: "%s" batch_size: 8 backend: LMDB }
  transform_param { crop_size: 10 mirror: true scale: 0.0078125
                    mean_value: 128 }
}
layers {
  name: "ip1" type: INNER_PRODUCT bottom: "data" top: "ip1"
  inner_product_param { num_output: 5
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "loss" type: SOFTMAX_LOSS bottom: "ip1" bottom: "label" top: "loss" }
""" % db)
    solver = tmp_path / "solver.prototxt"
    solver.write_text(f"""
net: "{net}"
base_lr: 0.05
lr_policy: "fixed"
momentum: 0.9
display: 0
max_iter: 6
snapshot: 0
snapshot_prefix: "snap/u8net"
random_seed: 5
""")
    sp = load_solver(str(solver))

    losses = {}
    for dev_t in (False, True):
        eng = Engine(sp, output_dir=str(tmp_path), device_transform=dev_t)
        try:
            if dev_t:
                assert eng._input_transform is not None, \
                    "device transform should engage on this config"
                assert next(iter(eng.train_pipelines)).device_transform_spec
            last = eng.train()
            losses[dev_t] = float(last["loss"])
        finally:
            eng.close()
    assert abs(losses[True] - losses[False]) < 1e-4, losses

    # SSP composes too (the step builder's input hook): u8 ingest + device
    # transform trains under staleness without error
    eng = Engine(sp, output_dir=str(tmp_path), device_transform=True,
                 staleness=1)
    try:
        assert eng._input_transform is not None
        last = eng.train()
        assert np.isfinite(last["loss"])
    finally:
        eng.close()


def test_engine_chunking_invariant_rng_stream(tmp_path):
    """K must not change training: the scan body folds rng by GLOBAL
    iteration (solver.it + offset), so a dropout net trains to identical
    losses whether dispatched singly or in chunks."""
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    net = tmp_path / "net.prototxt"
    net.write_text("""
name: "DropNet"
layers {
  name: "mnist" type: MEMORY_DATA top: "data" top: "label"
  memory_data_param { batch_size: 8 channels: 1 height: 12 width: 12 }
}
layers {
  name: "ip1" type: INNER_PRODUCT bottom: "data" top: "ip1"
  inner_product_param { num_output: 16
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "relu1" type: RELU bottom: "ip1" top: "ip1" }
layers { name: "drop1" type: DROPOUT bottom: "ip1" top: "ip1"
  dropout_param { dropout_ratio: 0.5 } }
layers {
  name: "ip2" type: INNER_PRODUCT bottom: "ip1" top: "ip2"
  inner_product_param { num_output: 5
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "loss" type: SOFTMAX_LOSS bottom: "ip2" bottom: "label" top: "loss" }
""")
    solver = tmp_path / "solver.prototxt"
    solver.write_text(f"""
net: "{net}"
base_lr: 0.05
lr_policy: "fixed"
momentum: 0.9
display: 0
max_iter: 6
snapshot: 0
snapshot_prefix: "snap/dropnet"
random_seed: 11
""")
    sp = load_solver(str(solver))
    losses = {}
    for k in (1, 3):
        eng = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path),
                     steps_per_dispatch=k)
        try:
            last = eng.train()
            losses[k] = float(last["loss"])
        finally:
            eng.close()
    assert abs(losses[1] - losses[3]) < 5e-5, losses


# --------------------------------------------------------------------------- #
# runtime/metrics.py direct unit tests (ISSUE 2 satellite): previously only
# exercised indirectly through Engine runs.
# --------------------------------------------------------------------------- #

def test_metrics_table_flush_row_averages_and_clears():
    from poseidon_tpu.runtime.metrics import MetricsTable

    t = MetricsTable("train")
    t.accumulate({"loss": 2.0, "acc": 0.5})
    t.accumulate({"loss": 4.0, "acc": 1.0})
    row = t.flush_row(10)
    assert row["iter"] == 10
    assert row["loss"] == 3.0 and row["acc"] == 0.75
    assert "time" in row
    # the window cleared: the next flush averages only NEW samples
    t.accumulate({"loss": 10.0})
    row2 = t.flush_row(20)
    assert row2["loss"] == 10.0 and "acc" not in row2
    assert [r["iter"] for r in t.rows] == [10, 20]


def test_metrics_table_to_csv_union_columns(tmp_path):
    from poseidon_tpu.runtime.metrics import MetricsTable

    t = MetricsTable("train")
    t.accumulate({"loss": 1.0})
    t.flush_row(1)
    t.accumulate({"loss": 2.0, "acc": 0.5})   # a column appears later
    t.flush_row(2)
    path = tmp_path / "out" / "m.csv"
    t.to_csv(str(path))
    lines = path.read_text().strip().splitlines()
    header = lines[0].split(",")
    assert header[:2] == ["iter", "time"] and "acc" in header
    first = dict(zip(header, lines[1].split(",")))
    assert first["acc"] == ""                 # missing cell stays blank
    second = dict(zip(header, lines[2].split(",")))
    assert float(second["acc"]) == 0.5


def test_stats_registry_accumulation_and_yaml(tmp_path):
    from poseidon_tpu.runtime.metrics import StatsRegistry

    s = StatsRegistry()
    s.add("train_iters")                      # default increment 1.0
    s.add("train_iters", 4.0)
    s.add_time("train_step", 0.25)
    s.add_time("train_step", 0.5)             # add_time ACCUMULATES
    s.add_time("io", 0.125)
    s.set_section("comm", {"summary": {"bytes": 128}, "note": None})
    assert s.counters["train_iters"] == 5.0
    assert s.timers["train_step"] == 0.75
    path = tmp_path / "stats.yaml"
    s.dump_yaml(str(path))
    text = path.read_text()
    assert "train_iters: 5.0" in text
    assert "train_step: 0.75" in text and "io: 0.125" in text
    assert "comm:" in text and "bytes: 128" in text
    assert "note: null" in text               # None serializes as yaml null


def test_latency_window_percentiles():
    from poseidon_tpu.runtime.metrics import LatencyWindow

    w = LatencyWindow(maxlen=100)
    assert w.percentile(50) is None and w.summary() == {"count": 0}
    for ms in range(1, 101):                  # 1..100 ms
        w.record(ms / 1e3)
    assert w.percentile(50) == pytest.approx(0.050, abs=0.002)
    assert w.percentile(99) == pytest.approx(0.099, abs=0.002)
    s = w.summary()
    assert s["count"] == 100
    assert s["p50_ms"] == pytest.approx(50.0, abs=2.0)
    assert s["p99_ms"] == pytest.approx(99.0, abs=2.0)
    # bounded window: old samples age out, count keeps the lifetime total
    for _ in range(100):
        w.record(1.0)
    assert w.percentile(50) == 1.0 and w.summary()["count"] == 200
