"""Two-tier training fabric (ISSUE 16): SPMD slices as elastic SSP workers.

The slice IS the worker: inside a slice the named dp/fsdp/tp mesh runs
the step synchronously over the slice's own device block; between
slices one leader process speaks the unmodified AsyncSSPClient protocol,
so staleness bounds, exactly-once, admit/retire and eviction all apply
at slice granularity with zero wire changes. These tests pin:

- the POSEIDON_SLICE_ID / POSEIDON_SLICE_SIZE env contract (loud
  all-or-nothing refusals; plain per-process mode unchanged when unset);
- two-tier data sharding and the arena-delta exchange hooks;
- leader failover: the successor re-derives the acked floor from the
  service and resumes the ledger's oplog — exactly-once across leader
  death, proven bitwise with power-of-two deltas through a severed
  FaultProxy link;
- the acceptance chaos run: 2 slices x dp2,fsdp2 real jitted sub-mesh
  steps on the 8-device virtual CPU mesh, through kill-slice +
  re-admit-slice, with loss continuity, zero gate deadlock, and the
  final anchor BITWISE equal to a fixed-membership replay of the same
  dispatched step sequence;
- protocol-trace conformance of a failure-free slice-granularity run
  (admit + retire of whole slices) against the model checker's rules.

Every socket binds port 0 on loopback — no fixed ports, no flakes.
"""

import time

import numpy as np
import pytest

import jax

from poseidon_tpu.analysis import model_check as M
from poseidon_tpu.config import (MeshConfig, fabric_config,
                                 set_fabric_config)
from poseidon_tpu.core.net import Net
from poseidon_tpu.data.workload import Shard
from poseidon_tpu.models import zoo
from poseidon_tpu.parallel import CommConfig, init_train_state
from poseidon_tpu.parallel.async_ssp import (AsyncSSPClient, ParamService,
                                             _tree_copy, _tree_sub)
from poseidon_tpu.parallel.fabric import (SliceWorker, arena_flat,
                                          arena_tree, pack_arena_delta,
                                          run_slice_worker,
                                          slice_device_block, slice_submesh,
                                          two_tier_shard,
                                          unpack_arena_cache)
from poseidon_tpu.parallel.spmd import ShardingPlan, build_spmd_train_step
from poseidon_tpu.proto.messages import SolverParameter
from poseidon_tpu.runtime.cluster import slice_env, slice_world
from poseidon_tpu.runtime.faults import FaultProxy

pytestmark = pytest.mark.fabric

FAST = dict(heartbeat_s=0.1, reconnect_deadline_s=5.0,
            backoff_base_s=0.01, backoff_cap_s=0.1)

SLICE_VARS = ("POSEIDON_SLICE_ID", "POSEIDON_SLICE_SIZE",
              "POSEIDON_PROC_ID", "POSEIDON_NUM_PROCS")


def _clean_env(monkeypatch):
    for v in SLICE_VARS:
        monkeypatch.delenv(v, raising=False)


def _zeros(shape=(2, 2)):
    return {"fc": {"w": np.zeros(shape, np.float32)}}


def _delta(v, shape=(2, 2)):
    return {"fc": {"w": np.full(shape, v, np.float32)}}


def _wait_for(pred, timeout_s=15.0, what="condition"):
    deadline = time.time() + timeout_s
    while not pred():
        if time.time() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.01)


def _tree_equal(a, b, what=""):
    assert set(a) == set(b)
    for l in a:
        for k in a[l]:
            np.testing.assert_array_equal(
                np.asarray(a[l][k]), np.asarray(b[l][k]),
                err_msg=f"{what} {l}/{k}")


# --------------------------------------------------------------------------- #
# slice env contract (runtime/cluster.py)
# --------------------------------------------------------------------------- #

def test_slice_env_unset_is_plain_mode(monkeypatch):
    """Neither variable set -> None: the per-process path stays
    byte-for-byte unchanged (the fabric is strictly opt-in)."""
    _clean_env(monkeypatch)
    assert slice_env() is None
    assert slice_world() is None


def test_slice_env_half_set_is_refused(monkeypatch):
    _clean_env(monkeypatch)
    monkeypatch.setenv("POSEIDON_SLICE_ID", "0")
    with pytest.raises(ValueError, match="all-or-nothing"):
        slice_env()
    _clean_env(monkeypatch)
    monkeypatch.setenv("POSEIDON_SLICE_SIZE", "2")
    with pytest.raises(ValueError, match="all-or-nothing"):
        slice_env()


def test_slice_env_impossible_values_are_refused(monkeypatch):
    _clean_env(monkeypatch)
    monkeypatch.setenv("POSEIDON_SLICE_ID", "-1")
    monkeypatch.setenv("POSEIDON_SLICE_SIZE", "2")
    with pytest.raises(ValueError, match="must be >= 0"):
        slice_env()
    monkeypatch.setenv("POSEIDON_SLICE_ID", "0")
    monkeypatch.setenv("POSEIDON_SLICE_SIZE", "0")
    with pytest.raises(ValueError, match="must be >= 1"):
        slice_env()
    monkeypatch.setenv("POSEIDON_SLICE_SIZE", "4")
    with pytest.raises(ValueError, match="cannot share a device"):
        slice_env(n_visible_devices=2)


def test_slice_world_contiguous_block_contract(monkeypatch):
    """slice k owns ranks [k*size, (k+1)*size); rank 0 of the block is
    the leader; a slice id past the roster is a joiner."""
    _clean_env(monkeypatch)
    monkeypatch.setenv("POSEIDON_NUM_PROCS", "4")
    monkeypatch.setenv("POSEIDON_SLICE_SIZE", "2")
    monkeypatch.setenv("POSEIDON_SLICE_ID", "1")
    monkeypatch.setenv("POSEIDON_PROC_ID", "2")
    sw = slice_world()
    assert (sw.slice_id, sw.rank_in_slice, sw.n_slices) == (1, 0, 2)
    assert sw.is_leader and not sw.is_joiner_slice
    monkeypatch.setenv("POSEIDON_PROC_ID", "3")
    sw = slice_world()
    assert sw.rank_in_slice == 1 and not sw.is_leader
    # elastic joiner slice: ranks past the roster, whole slice admitted
    monkeypatch.setenv("POSEIDON_SLICE_ID", "2")
    monkeypatch.setenv("POSEIDON_PROC_ID", "4")
    sw = slice_world()
    assert sw.is_joiner_slice and sw.is_leader


def test_slice_world_refuses_overlap_and_orphan_ranks(monkeypatch):
    _clean_env(monkeypatch)
    monkeypatch.setenv("POSEIDON_NUM_PROCS", "4")
    monkeypatch.setenv("POSEIDON_SLICE_SIZE", "2")
    monkeypatch.setenv("POSEIDON_SLICE_ID", "1")
    monkeypatch.setenv("POSEIDON_PROC_ID", "0")   # rank 0 is slice 0's
    with pytest.raises(ValueError, match="overlapping slice assignment"):
        slice_world()
    monkeypatch.setenv("POSEIDON_PROC_ID", "2")
    monkeypatch.setenv("POSEIDON_NUM_PROCS", "5")  # 5 % 2 != 0
    with pytest.raises(ValueError, match="whole number"):
        slice_world()


# --------------------------------------------------------------------------- #
# two-tier sharding + arena exchange hooks (parallel/fabric.py units)
# --------------------------------------------------------------------------- #

def test_two_tier_shard_composes_outer_and_inner_cuts():
    """outer cut by live slice ids, inner by live member ranks: the
    composed shards are disjoint and cover record space; a slice retire
    re-cuts the outer tier, a member loss only the inner tier."""
    # 2 slices x 2 members -> 4 disjoint shards of count 4
    got = {two_tier_shard([0, 1], s, [0, 1], r)
           for s in (0, 1) for r in (0, 1)}
    assert got == {Shard(i, 4) for i in range(4)}
    # slice 1 retired: slice 0's members re-key to count 2
    assert two_tier_shard([0], 0, [0, 1], 1) == Shard(1, 2)
    # slice 0 lost member 0: inner re-cut only (outer count unchanged)
    assert two_tier_shard([0, 1], 0, [1], 1) == Shard(0, 2)
    # non-member lookups refuse loudly (member_shard's contract)
    with pytest.raises(ValueError):
        two_tier_shard([0, 1], 0, [0, 1], 7)


def test_slice_device_block_is_contiguous_and_bounded():
    devs = list(range(8))
    assert slice_device_block(devs, 0, 4) == [0, 1, 2, 3]
    assert slice_device_block(devs, 1, 4) == [4, 5, 6, 7]
    with pytest.raises(ValueError, match="contiguous"):
        slice_device_block(devs, 2, 4)


class _TinyLayout:
    """Duck-typed stand-in for core/arena.ArenaLayout: the fabric hooks
    only rely on the pack/unpack pair being exact inverses."""

    def pack(self, tree):
        return np.concatenate([tree["a"]["w"].ravel(),
                               tree["b"]["w"].ravel()]).astype(np.float32)

    def unpack(self, flat):
        flat = np.asarray(flat, np.float32)
        return {"a": {"w": flat[:4].reshape(2, 2).copy()},
                "b": {"w": flat[4:6].copy()}}


def test_arena_delta_hooks_roundtrip_bitwise():
    """pack_arena_delta -> wire -> unpack_arena_cache is exact: the DCN
    tier pushes ONE flat leaf (global TOPK ranking over the whole slice
    update) and the per-leaf tree survives the round trip bitwise."""
    layout = _TinyLayout()
    rng = np.random.RandomState(3)
    params = {"a": {"w": rng.randn(2, 2).astype(np.float32)},
              "b": {"w": rng.randn(2).astype(np.float32)}}
    prev = np.zeros(6, np.float32)
    delta, flat = pack_arena_delta(layout, params, prev)
    assert set(delta) == {"arena"} and arena_flat(delta).shape == (6,)
    np.testing.assert_array_equal(arena_flat(delta), flat - prev)
    np.testing.assert_array_equal(arena_flat(arena_tree(flat)), flat)
    _tree_equal(unpack_arena_cache(layout, arena_tree(flat)), params,
                "arena roundtrip")
    # incremental: prev + delta reconstructs the new flat view bitwise
    delta2, flat2 = pack_arena_delta(layout, params, flat)
    np.testing.assert_array_equal(arena_flat(delta2), np.zeros(6, np.float32))
    np.testing.assert_array_equal(flat2, flat)


# --------------------------------------------------------------------------- #
# resume_oplog: the failover primitive (parallel/async_ssp.py)
# --------------------------------------------------------------------------- #

def test_resume_oplog_rederives_floor_and_replays_only_above_it():
    """The successor's acked floor comes from the SERVICE's applied
    table, not the dead leader's memory: ledger entries at or below it
    are never re-sent, entries above replay with their original seqs,
    and the post-resume push stream continues past the high-water."""
    svc = ParamService(_zeros(), n_workers=1, liveness_timeout_s=0.0)
    addr = ("127.0.0.1", svc.port)
    d0, d1 = _delta(1.0), _delta(2.0)
    a = AsyncSSPClient(0, addr, 1, n_workers=1, **FAST)
    a.push(_tree_copy(d0))
    _wait_for(lambda: svc.clocks[0] >= 0, what="clock 0 applied")
    a.abandon()                      # leader death: no flush, no bye
    # the mirrored ledger: clock 1's payload never made it out; clock 0
    # rides the ledger too (a stale-but-superset mirror must be safe)
    pending = [(0, _tree_copy(d0), True), (1, _tree_copy(d1), True)]
    b = AsyncSSPClient(0, addr, 1, n_workers=1, **FAST)
    try:
        floor = b.resume_oplog(1, pending, _tree_copy(d1))
        assert floor == 0 and b.clock == 1
        np.testing.assert_array_equal(b._residual["fc"]["w"],
                                      d1["fc"]["w"])
        _wait_for(lambda: svc.clocks[0] >= 1, what="replayed clock 1")
        np.testing.assert_array_equal(
            svc.anchor["fc"]["w"], np.full((2, 2), 3.0, np.float32))
        # seq stream resumes PAST the high-water: the next flush is not
        # swallowed by dedup and not double-applied, and the restored
        # residual rides it out exactly once (4 + parked 2 = 6 on top of
        # the 3 already anchored) — no parked bytes die with the leader
        assert b.push(_delta(4.0)) == 2
        _wait_for(lambda: svc.clocks[0] >= 2, what="post-resume push")
        np.testing.assert_array_equal(
            svc.anchor["fc"]["w"], np.full((2, 2), 9.0, np.float32))
        b.mark_done()
    finally:
        b.close()
        svc.close()


# --------------------------------------------------------------------------- #
# slice membership events via the run_slice_worker driver
# --------------------------------------------------------------------------- #

def test_slice_shrink_recuts_inner_shard_and_keeps_training():
    """A non-leader member loss shrinks the slice: the inner data cut
    re-keys over the survivors and the DCN stream never blinks."""
    svc = ParamService(_zeros(), n_workers=1, liveness_timeout_s=0.0)
    w = SliceWorker(0, [0, 1, 2], ("127.0.0.1", svc.port), 1,
                    n_slices=1, client_opts=FAST)
    try:
        assert w.data_shard([0], rank=2) == Shard(2, 3)

        def step(cache, i):
            return ({l: {p: v + 1.0 for p, v in ps.items()}
                     for l, ps in cache.items()}, 0.0)

        out = run_slice_worker(w, _zeros(), step, n_clocks=3,
                               fail_at={1: [1]})
        assert out["events"] == [(1, "shrunk:1")]
        assert out["failovers"] == 0 and not out["retired"]
        assert w.data_shard([0], rank=2) == Shard(1, 2)
        _wait_for(lambda: svc.clocks[0] >= 2, what="3 clocks applied")
        np.testing.assert_array_equal(
            svc.anchor["fc"]["w"], np.full((2, 2), 3.0, np.float32))
    finally:
        w.close()
        svc.close()


def test_slice_below_min_members_retires_cleanly():
    """Falling below FabricConfig.min_members retires the slice's DCN
    slot (flush + retire RPC) so survivors' gates stop counting it; a
    leader death on the way down still fails over first, so the retire
    flush carries the full oplog."""
    old_min = fabric_config().min_members
    set_fabric_config(min_members=2)
    svc = ParamService(_zeros(), n_workers=1, liveness_timeout_s=0.0)
    w = None
    try:
        w = SliceWorker(0, [0, 1], ("127.0.0.1", svc.port), 1,
                        n_slices=1, client_opts=FAST)

        def step(cache, i):
            return ({l: {p: v + 1.0 for p, v in ps.items()}
                     for l, ps in cache.items()}, 0.0)

        out = run_slice_worker(w, _zeros(), step, n_clocks=4,
                               fail_at={2: [0]})   # the LEADER dies
        assert out["events"] == [(2, "retired:0")]
        assert out["retired"] and out["failovers"] == 1
        assert 0 in svc.retired
        # clocks 0 and 1 flushed before the event; nothing after
        _wait_for(lambda: svc.clocks[0] >= 1, what="pre-retire clocks")
        np.testing.assert_array_equal(
            svc.anchor["fc"]["w"], np.full((2, 2), 2.0, np.float32))
    finally:
        set_fabric_config(min_members=old_min)
        if w is not None:
            w.close()
        svc.close()


# --------------------------------------------------------------------------- #
# leader failover: exactly-once across leader death (the tentpole pin)
# --------------------------------------------------------------------------- #

def test_leader_failover_exactly_once_through_severed_links():
    """The leader's links are cut mid-window (runtime/faults.sever_group
    — the targeted half of a partition) and the slice fails over: the
    successor re-derives the acked floor and resumes the ledger. Deltas
    are DISTINCT POWERS OF TWO, so the final anchor is bitwise the exact
    sum iff every (slice, clock) delta applied exactly once — a lost
    replay or a double apply each perturb at least one mantissa bit."""
    N = 4
    svc = ParamService(_zeros((1,)), n_workers=2, record_events=True,
                       liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    slices = [SliceWorker(0, [0, 1], proxy.addr, 1, n_slices=2,
                          client_opts=FAST),
              SliceWorker(1, [0, 1], proxy.addr, 1, n_slices=2,
                          client_opts=FAST)]
    try:
        for s in slices:
            s.join()
        for clock in range(N):
            if clock == 2:
                # kill slice 0's leader between windows: its clock-1 ack
                # may or may not have landed — both paths must be
                # exactly-once (lost-ack replay dedups by seq)
                assert proxy.sever_group({0}) >= 1
                assert slices[0].fail_member(0) == "failover"
                assert slices[0].leader == 1
                assert slices[0].failovers == 1
            for sid, s in enumerate(slices):
                s.gate(clock, timeout_s=60)
                s.push(_delta(2.0 ** (sid * 16 + clock), shape=(1,)))
        _wait_for(lambda: svc.clocks == {0: N - 1, 1: N - 1},
                  what="all slice clocks applied")
        expected = np.float32(sum(2.0 ** (sid * 16 + c)
                                  for sid in (0, 1) for c in range(N)))
        got = svc.anchor["fc"]["w"][0]
        assert got == expected, (
            f"anchor {got!r} != {expected!r}: a delta was lost or "
            f"double-applied across the failover")
        # the event log agrees: every (worker, clock) applied once
        applied = [(e[1], e[2]) for e in svc.events
                   if e[0] == "push" and not e[4]]
        assert len(applied) == len(set(applied))
        assert slices[0].ledger.mirrors >= N + 1   # re-mirrored at failover
    finally:
        for s in slices:
            s.close()
        proxy.close()
        svc.close()


# --------------------------------------------------------------------------- #
# protocol conformance at slice granularity (admit + retire whole slices)
# --------------------------------------------------------------------------- #

def test_slice_granularity_run_conforms_to_protocol_model():
    """A failure-free 3-slice run — launch roster of 2, one retires
    mid-run, a joiner slice is admitted at the rendezvous clock — replays
    cleanly through the model checker's service-state rules: the slice id
    IS a worker id, so every pinned protocol property carries over by
    config, not by new code."""
    svc = ParamService(_zeros(), n_workers=2, record_events=True)
    addr = ("127.0.0.1", svc.port)
    w0 = SliceWorker(0, [0, 1], addr, 0, n_slices=2, client_opts=FAST)
    w1 = SliceWorker(1, [0, 1], addr, 0, n_slices=2, client_opts=FAST)
    w2 = None
    try:
        for clock in range(2):
            for s in (w0, w1):
                s.gate(clock, timeout_s=60)
                s.push(_delta(1.0))
        w1.retire()
        _wait_for(lambda: svc.clocks[0] >= 1, what="roster clocks applied")
        w2 = SliceWorker(2, [0], addr, 0, n_slices=2, client_opts=FAST)
        cache, clocks = w2.join()          # whole-slice admit mid-run
        assert w2.client.clock >= 1        # anchored at rendezvous clock
        _tree_equal(cache, svc.anchor, "join anchor")
        for clock in range(2, 4):
            for s in (w0, w2):
                s.gate(clock, timeout_s=60)
                s.push(_delta(1.0))
        w0.mark_done()
        w2.mark_done()
        _wait_for(lambda: svc.clocks[0] >= 3, what="final clocks")
        counts = M.conform_service_events(list(svc.events), staleness=0,
                                          n_workers=2)
        assert counts["push"] >= 7         # 4 + 2 + >=1 (w2's windows)
        assert counts["retire"] == 1
        assert counts["admit"] >= 1        # the joiner slice's rendezvous
    finally:
        for s in (w0, w1, w2):
            if s is not None:
                s.close()
        svc.close()


# --------------------------------------------------------------------------- #
# the acceptance chaos run: 2 slices x dp2,fsdp2, kill + re-admit, bitwise
# --------------------------------------------------------------------------- #

SP = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                     weight_decay=0.0005)
BATCH = 16
N_CLOCKS = 5
KILL, REJOIN = 2, 3
STALE = 1


def _np_tree(tree):
    return {l: {p: np.asarray(v) for p, v in ps.items()}
            for l, ps in tree.items()}


def _fabric_batch(slice_id, clock):
    rng = np.random.RandomState(123 + 17 * slice_id + clock)
    return {"data": rng.randn(BATCH, 1, 28, 28).astype(np.float32),
            "label": rng.randint(0, 10, size=(BATCH,))}


def test_two_slice_chaos_bitwise_replay():
    """The acceptance run. Two SliceWorkers, each running REAL jitted
    SPMD steps on its own dp2,fsdp2 sub-mesh (contiguous 4-device blocks
    of the 8-device virtual CPU mesh). Slice 1 is killed at a clock
    boundary (every member lost, sockets die raw), the survivor's gates
    keep passing (zero deadlock), and a fresh slice re-admits under the
    same id, warm-starting from the already-compiled step and anchoring
    at the service's rendezvous clock. The final anchor is BITWISE equal
    to a fixed-membership replay that dispatches the same step sequence
    (same batches, same keys, same apply order) with slice 1 merely
    pausing over the dead window — membership chaos changed WHEN updates
    flowed, never WHAT they computed."""
    cfg = MeshConfig.parse("dp2,fsdp2")
    net = Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
              source_shapes=zoo.lenet_shapes(BATCH // 4))
    comm = CommConfig()
    plan = ShardingPlan.build(net, cfg, comm)
    params0 = net.init(jax.random.PRNGKey(0))
    init_np = _np_tree(params0)
    # one compiled step per device block; the re-admitted slice 1 reuses
    # the SAME executable — the compile-cache warm-start in test form
    steps = [build_spmd_train_step(
                 net, SP, slice_submesh(cfg, sid), plan, comm,
                 donate=False)
             for sid in (0, 1)]
    state0 = init_train_state(params0, comm, plan.n_dp)

    def drive(chaos):
        svc = ParamService(_np_tree(init_np), n_workers=2,
                           record_events=True, liveness_timeout_s=0.0)
        addr = ("127.0.0.1", svc.port)
        sw = [SliceWorker(0, [0, 1], addr, STALE, n_slices=2,
                          client_opts=FAST),
              SliceWorker(1, [0, 1], addr, STALE, n_slices=2,
                          client_opts=FAST)]
        caches = [c for c, _ in (sw[0].join(), sw[1].join())]
        states = [state0, state0]
        losses = {0: [], 1: []}

        def dispatch(sid, clock):
            w = sw[sid]
            w.gate(clock, timeout_s=60)
            prev = _tree_copy(caches[sid])
            p, s, m = steps[sid].step(caches[sid], states[sid],
                                      _fabric_batch(sid, clock),
                                      jax.random.fold_in(
                                          jax.random.PRNGKey(42),
                                          100 * sid + clock))
            states[sid] = s
            caches[sid] = _np_tree(p)
            losses[sid].append((clock, float(m["loss"])))
            pushed = w.push(_tree_sub(caches[sid], prev))
            # pin the apply order: the next dispatch must see this
            # update in the anchor, in both arms, for bitwise replay
            _wait_for(lambda: w.client.poll_view().get(sid, -1) >= pushed,
                      what=f"slice {sid} clock {pushed} applied")
            caches[sid], _ = w.refresh()

        try:
            for clock in range(N_CLOCKS):
                if chaos and clock == KILL:
                    # whole-slice death: shrink, then the last member
                    assert sw[1].fail_member(1) == "shrunk"
                    assert sw[1].fail_member(0) == "dead"
                    sw[1].client.abandon()
                    _wait_for(lambda: 1 in svc.failed_workers,
                              what="slice 1 evicted")
                if clock == REJOIN:
                    if chaos:
                        sw[1] = SliceWorker(1, [10, 11], addr, STALE,
                                            n_slices=2, client_opts=FAST)
                        caches[1], _ = sw[1].join()
                        # the rendezvous rule for a re-admitted id:
                        # resume past its OWN historical high-water
                        # (its last flushed clock before death), never
                        # behind it — the clock stream continues as if
                        # the dead window were a pause
                        assert sw[1].client.clock == KILL - 1, \
                            "rejoined slice must anchor at the " \
                            "rendezvous clock"
                    else:
                        caches[1], _ = sw[1].refresh()
                    states[1] = state0   # warm start = anchor + fresh state
                dispatch(0, clock)
                if not (KILL <= clock < REJOIN):
                    dispatch(1, clock)
            sw[0].mark_done()
            sw[1].mark_done()
            _wait_for(lambda: svc.clocks[0] >= N_CLOCKS - 1,
                      what="final survivor clock")
            anchor = _tree_copy(svc.anchor)
            applied = [(e[1], e[2]) for e in svc.events
                       if e[0] == "push" and not e[4]]
            return {"anchor": anchor, "losses": losses,
                    "applied": applied, "rejoins": svc.rejoins,
                    "blocked_s": sw[0].client.blocked_s}
        finally:
            for s in sw:
                s.close()
            svc.close()

    chaos = drive(chaos=True)
    replay = drive(chaos=False)

    # exactly-once through the chaos: every (slice, clock) applied once
    assert len(chaos["applied"]) == len(set(chaos["applied"]))
    assert chaos["rejoins"] >= 1
    # the acceptance pin: membership chaos is bitwise-invisible in the
    # final parameters
    _tree_equal(chaos["anchor"], replay["anchor"], "chaos vs replay")
    # loss continuity, in the strongest sense: the chaos trajectory is
    # finite throughout and EQUALS the fixed-membership replay's loss
    # sequence bitwise, per slice per clock — the kill/re-admit left no
    # trace in what either slice computed, only in when it flowed
    assert all(np.isfinite(v) for ls in chaos["losses"].values()
               for _, v in ls)
    assert chaos["losses"] == replay["losses"]
    # and the rejoined slice really did dispatch after the dead window
    assert [c for c, _ in chaos["losses"][1]] == \
        [c for c in range(N_CLOCKS) if not (KILL <= c < REJOIN)]


# --------------------------------------------------------------------------- #
# FabricTier: the engine hook (train --async_ssp --slice)
# --------------------------------------------------------------------------- #

def test_fabric_tier_leader_speaks_as_slice_id(monkeypatch):
    """Under the slice env the tier's DCN identity is the SLICE id and
    the roster is counted in whole slices; the leader owns the ledger."""
    from poseidon_tpu.runtime.async_tier import FabricTier
    _clean_env(monkeypatch)
    monkeypatch.setenv("POSEIDON_NUM_PROCS", "4")
    monkeypatch.setenv("POSEIDON_SLICE_SIZE", "2")
    monkeypatch.setenv("POSEIDON_SLICE_ID", "0")
    monkeypatch.setenv("POSEIDON_PROC_ID", "0")
    tier = FabricTier(_zeros(), staleness=1, service_port=0,
                      liveness_timeout_s=0.0)
    try:
        assert (tier.rank, tier.n_procs) == (0, 2)   # slice 0 of 2 slices
        assert tier.slice_assignment.is_leader
        assert tier.service is not None and tier.service.n_workers == 2
        # the flush hook mirrors the oplog into the slice ledger
        tier.client.push(_delta(1.0))
        tier._mirror()
        assert tier.ledger.mirrors == 1
        clock, pending, _ = tier.ledger.snapshot()
        assert clock == 0
        tier.client.mark_done()
    finally:
        tier.client.close()
        tier.service.close()


def test_fabric_tier_refuses_non_leader_and_missing_env(monkeypatch):
    from poseidon_tpu.runtime.async_tier import FabricTier
    _clean_env(monkeypatch)
    with pytest.raises(ValueError, match="requires the slice env"):
        FabricTier(_zeros(), staleness=1)
    monkeypatch.setenv("POSEIDON_NUM_PROCS", "4")
    monkeypatch.setenv("POSEIDON_SLICE_SIZE", "2")
    monkeypatch.setenv("POSEIDON_SLICE_ID", "0")
    monkeypatch.setenv("POSEIDON_PROC_ID", "1")   # rank-in-slice 1
    with pytest.raises(ValueError, match="not the leader"):
        FabricTier(_zeros(), staleness=1)
