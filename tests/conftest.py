"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax imports.

This is the deterministic in-process fake of the distributed substrate
(SURVEY.md §4): every parallel strategy is unit-tested on 8 virtual devices,
no TPU pod required.
"""

import os

# POSEIDON_TEST_TPU=1 runs the suite against the real TPU backend instead
# of the virtual CPU mesh — used by scripts/tpu_evidence.py to
# Mosaic-compile the Pallas kernels on hardware (tests/test_pallas.py).
_ON_TPU = os.environ.get("POSEIDON_TEST_TPU", "") == "1"

if not _ON_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU-tunnel plugin (if registered by sitecustomize) forces
# jax_platforms="axon,cpu" via jax.config, which overrides the env var and
# would route these CPU-mesh tests at a (possibly absent) TPU tunnel. Force
# the config back to cpu-only before any backend is initialized.
if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng_np():
    return np.random.RandomState(0)

def pattern_batch(rs, b, s, vocab):
    """The LM test-suite task: t[i+1] = (3 t[i] + 1) mod vocab — learnable
    by a tiny decoder in ~100 steps. Returns (tokens, targets), each (b, s).
    Shared by the transformer/moe/generate/checkpoint suites."""
    import jax.numpy as jnp
    start = rs.randint(0, vocab, size=(b, 1))
    seq = [start]
    for _ in range(s):
        seq.append((seq[-1] * 3 + 1) % vocab)
    full = np.concatenate(seq, axis=1)
    return jnp.asarray(full[:, :s]), jnp.asarray(full[:, 1:s + 1])
