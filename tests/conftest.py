"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax imports.

This is the deterministic in-process fake of the distributed substrate
(SURVEY.md §4): every parallel strategy is unit-tested on 8 virtual devices,
no TPU pod required.
"""

import os

# POSEIDON_TEST_TPU=1 runs the suite against the real TPU backend instead
# of the virtual CPU mesh — used by scripts/tpu_evidence.py to
# Mosaic-compile the Pallas kernels on hardware (tests/test_pallas.py).
_ON_TPU = os.environ.get("POSEIDON_TEST_TPU", "") == "1"

if not _ON_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU-tunnel plugin (if registered by sitecustomize) forces
# jax_platforms="axon,cpu" via jax.config, which overrides the env var and
# would route these CPU-mesh tests at a (possibly absent) TPU tunnel. Force
# the config back to cpu-only before any backend is initialized.
if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")

import faulthandler  # noqa: E402
import threading  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# ---- thread sanitizer (ISSUE 8 satellite) -------------------------------- #
# A wedged suite dumps every stack on SIGABRT/timeout instead of dying mute.
faulthandler.enable()

# Uncaught exceptions on background threads historically vanished into
# stderr while the test that caused them passed. Record them and fail the
# test they happened under (mark `allow_thread_exceptions` for tests that
# intentionally kill threads rudely).
_THREAD_ERRORS = []          # (thread object, rendered message)
_ORIG_EXCEPTHOOK = threading.excepthook


def _failing_excepthook(args):
    # keep the Thread OBJECT, not its ident: CPython recycles idents, so
    # an ident-keyed filter could blame (or absolve) the wrong thread
    _THREAD_ERRORS.append((args.thread,
                           f"{getattr(args.thread, 'name', '?')}: "
                           f"{args.exc_type.__name__}: {args.exc_value}"))
    _ORIG_EXCEPTHOOK(args)


threading.excepthook = _failing_excepthook


@pytest.fixture(autouse=True)
def _thread_sanitizer(request):
    """Per-test teardown gate: no uncaught background-thread exception,
    and no NEW non-daemon thread may survive the test (a leaked
    non-daemon thread wedges interpreter shutdown — the repo's own
    threads are all daemonic by policy, so survivors are test bugs).

    Only exceptions from threads STARTED during this test fail it: a
    daemon thread from an earlier test dying late must not be blamed on
    whichever test happens to be running when it unwinds."""
    errs_before = len(_THREAD_ERRORS)
    before = set(threading.enumerate())
    yield
    # run BOTH checks before failing: a test whose thread raises AND
    # wedges must still get its leak joined/reported, or the survivor
    # haunts later tests unattributed
    problems = []
    new_errs = [msg for t, msg in _THREAD_ERRORS[errs_before:]
                if t not in before]
    if new_errs and not request.node.get_closest_marker(
            "allow_thread_exceptions"):
        problems.append("uncaught exception on background thread(s): "
                        + "; ".join(new_errs))
    leaked = [t for t in threading.enumerate()
              if not t.daemon and t.is_alive() and t not in before]
    for t in leaked:
        t.join(timeout=2.0)     # grace: racing a clean close() is fine
    leaked = [t for t in leaked if t.is_alive()]
    if leaked:
        problems.append("non-daemon thread(s) leaked by test: "
                        + ", ".join(t.name for t in leaked))
    if problems:
        pytest.fail("; ".join(problems), pytrace=False)


@pytest.fixture
def rng_np():
    return np.random.RandomState(0)

def pattern_batch(rs, b, s, vocab):
    """The LM test-suite task: t[i+1] = (3 t[i] + 1) mod vocab — learnable
    by a tiny decoder in ~100 steps. Returns (tokens, targets), each (b, s).
    Shared by the transformer/moe/generate/checkpoint suites."""
    import jax.numpy as jnp
    start = rs.randint(0, vocab, size=(b, 1))
    seq = [start]
    for _ in range(s):
        seq.append((seq[-1] * 3 + 1) % vocab)
    full = np.concatenate(seq, axis=1)
    return jnp.asarray(full[:, :s]), jnp.asarray(full[:, 1:s + 1])
