"""Elastic membership for the async-SSP tier (ISSUE 6).

PR 1 made the tier survive failures (eviction, reconnect, rejoin); these
tests pin the other half — the member set CHANGING under a live job:

- admit: a worker id outside the launch roster joins at the service-picked
  rendezvous anchor clock, pulls anchor + clock table, and its pushes ride
  the same exactly-once seq dedup as everyone else's;
- retire: a deliberate departure removes the slot from every gate's
  denominator (eviction only excludes; retirement removes);
- the acceptance chaos scenario: a FaultProxy-backed 1 -> 3 -> 2 scale
  sequence with loss continuity, every clock applied exactly once, no SSP
  gate deadlock across membership changes, and the final anchor BITWISE
  equal to a fixed-membership run of the same dispatched step sequence;
- resharded data assignment keyed by (member list, epoch);
- fast restart: persistent compile cache + the AOT step-executable store
  that make elasticity cheap.

Everything socket-level is deterministic: port-0 loopback binds, explicit
clock orchestration from the test thread (no wall-clock races decide which
clocks land), deltas that are distinct powers of two so the anchor SUM is
a bit-exact record of exactly which (worker, clock) increments applied —
a duplicate or dropped apply cannot hide.
"""

import socket
import threading
import time

import numpy as np
import pytest

from poseidon_tpu.data.workload import (Shard, elastic_shard_indices,
                                        member_shard)
from poseidon_tpu.parallel.async_ssp import (AsyncSSPClient, ParamService,
                                             run_async_ssp_worker)
from poseidon_tpu.runtime.faults import FaultProxy, FaultRule

# tight knobs so every reconnect/eviction resolves in test time
FAST = dict(heartbeat_s=0.1, reconnect_deadline_s=5.0,
            backoff_base_s=0.01, backoff_cap_s=0.1)


def _zeros64(shape=(2, 2)):
    # float64 anchor: sums of DISTINCT powers of two (the test deltas) are
    # exact for exponents spanning < 53 bits, so the final anchor is a
    # bit-exact set-membership record of applied (worker, clock) pairs
    return {"fc": {"w": np.zeros(shape, np.float64)}}


def _delta(w, c, shape=(2, 2)):
    """The (worker, clock) increment: a unique power of two per pair."""
    assert 0 <= c < 16 and 0 <= w < 3
    return {"fc": {"w": np.full(shape, 2.0 ** (w * 16 + c), np.float64)}}


def _expected(pairs, shape=(2, 2)):
    total = sum(2.0 ** (w * 16 + c) for w, c in pairs)
    return np.full(shape, total, np.float64)


def _wait_for(pred, timeout_s=10.0, what="condition"):
    deadline = time.time() + timeout_s
    while not pred():
        if time.time() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.01)


# --------------------------------------------------------------------------- #
# admit: rendezvous at the anchor clock
# --------------------------------------------------------------------------- #

def test_admit_new_worker_joins_at_anchor_clock():
    """A worker id outside n_workers joins mid-run: the service picks the
    join clock (min applied clock over live members), hands back anchor +
    clocks + member list, and the joiner's pushes apply exactly once from
    join_clock + 1. Both sides' gates run over the grown member set."""
    svc = ParamService(_zeros64(), n_workers=1, liveness_timeout_s=0.0)
    cli0 = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=1,
                          n_workers=1, **FAST)
    cli1 = None
    try:
        for c in range(3):
            cli0.gate(c, timeout_s=10.0)
            cli0.push(_delta(0, c))
        cli0._drain()
        assert svc.clocks[0] == 2

        cli1 = AsyncSSPClient(1, ("127.0.0.1", svc.port), staleness=1,
                              n_workers=1, **FAST)
        cache, clocks = cli1.join()
        # rendezvous anchor clock = min live clock = w0's clock
        assert cli1.clock == 2 and cli1._acked_clock == 2
        assert clocks[1] == 2
        assert cli1.members == {0, 1}
        assert svc.members == {0, 1}
        assert svc.admissions == 1
        # the joiner's cache is the anchor: every applied increment visible
        np.testing.assert_array_equal(
            cache["fc"]["w"], _expected([(0, 0), (0, 1), (0, 2)]))

        # joiner contributes from join_clock + 1; exactly-once
        cli1.gate(3, timeout_s=10.0)
        cli1.push(_delta(1, 3))
        cli1._drain()
        assert svc.clocks[1] == 3 and svc.applied_seq[1] == 3
        np.testing.assert_array_equal(
            svc.anchor["fc"]["w"],
            _expected([(0, 0), (0, 1), (0, 2), (1, 3)]))

        # w0's next ack folds the new member into its gate view
        cli0.push(_delta(0, 3))
        cli0._drain()
        assert cli0.members == {0, 1}
        # gate within the window returns immediately for both
        assert cli0.gate(4, timeout_s=10.0) == 0.0
        assert cli1.gate(4, timeout_s=10.0) == 0.0
    finally:
        for c in (cli0, cli1):
            if c is not None:
                c.close()
        svc.close()


def test_admit_is_idempotent_for_existing_member():
    """join() by an id that is already a member degenerates to the rejoin
    pull: resume at the applied clock, no admissions bump — one join path
    serves fresh workers, restarts, and true admissions alike."""
    svc = ParamService(_zeros64(), n_workers=2, liveness_timeout_s=0.0)
    cli0 = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=0,
                          n_workers=2, **FAST)
    try:
        cli0.push(_delta(0, 0))
        cli0._drain()
        cache, clocks = cli0.join()
        assert cli0.clock == 0 and cli0._acked_clock == 0
        assert svc.admissions == 0
        assert svc.members == {0, 1}
        np.testing.assert_array_equal(cache["fc"]["w"],
                                      _expected([(0, 0)]))
    finally:
        cli0.close()
        svc.close()


def test_readmitted_id_resumes_past_its_seq_high_water_mark():
    """A previously retired id that is admitted again must resume its
    push-seq stream PAST everything it ever flushed — otherwise the
    exactly-once dedup would swallow its post-readmission flushes (the
    healthy-looking-but-contributing-nothing failure mode)."""
    svc = ParamService(_zeros64(), n_workers=2, liveness_timeout_s=0.0)
    cli1 = AsyncSSPClient(1, ("127.0.0.1", svc.port), staleness=0,
                          n_workers=2, **FAST)
    try:
        for c in range(5):
            cli1.push(_delta(1, c))
        cli1.leave()
        assert svc.members == {0} and svc.retired == {1}
        cli1.close()

        # the same id comes back while the fleet idles at lower clocks
        cli1 = AsyncSSPClient(1, ("127.0.0.1", svc.port), staleness=0,
                              n_workers=2, **FAST)
        cli1.join()
        # NOT the anchor min (worker 0 sits at -1): its own high-water mark
        assert cli1.clock == 4
        cli1.push(_delta(1, 5))
        cli1._drain()
        assert svc.applied_seq[1] == 5  # applied, not swallowed
        np.testing.assert_array_equal(
            svc.anchor["fc"]["w"],
            _expected([(1, c) for c in range(6)]))
    finally:
        cli1.close()
        svc.close()


# --------------------------------------------------------------------------- #
# retire: the slot leaves the gates
# --------------------------------------------------------------------------- #

def test_retire_removes_slot_from_gates():
    """After a deliberate departure the survivor's gate stops counting the
    retired slot IMMEDIATELY (no liveness timeout involved): a gate that
    the retired worker's frozen clock would violate unblocks as soon as
    the survivor's poll sees the shrunken member list."""
    svc = ParamService(_zeros64(), n_workers=2, liveness_timeout_s=0.0)
    cli0 = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=0,
                          n_workers=2, **FAST)
    cli1 = AsyncSSPClient(1, ("127.0.0.1", svc.port), staleness=0,
                          n_workers=2, **FAST)
    try:
        cli1.push(_delta(1, 0))
        cli1.leave()   # drains, then retires the slot
        assert svc.retired == {1} and svc.members == {0}

        for c in range(4):
            cli0.push(_delta(0, c))
        # s=0, clock 4: needs every OTHER member at >= 3; worker 1 is
        # frozen at 0, so pre-retire this would block to the timeout
        waited = cli0.gate(4, poll_s=0.01, timeout_s=5.0)
        assert waited < 2.0, f"gate did not unblock on retirement: {waited}"
        assert 1 not in cli0.members
    finally:
        cli0.close()
        cli1.close()
        svc.close()


# --------------------------------------------------------------------------- #
# one-shot nth fault rule
# --------------------------------------------------------------------------- #

def test_one_shot_nth_rule_fires_on_exactly_the_nth_match():
    """FaultRule(nth=N) fires on exactly the Nth connection passing its
    filters, then expires: earlier connections pass untouched, later ones
    too — the targeting primitive count-based rules cannot express."""
    # minimal echo upstream
    srv = socket.create_server(("127.0.0.1", 0))
    stop = threading.Event()

    def echo_loop():
        srv.settimeout(0.1)
        while not stop.is_set():
            try:
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return

            def pump(c):
                try:
                    while True:
                        d = c.recv(1024)
                        if not d:
                            return
                        c.sendall(d)
                except OSError:
                    pass
                finally:
                    c.close()

            threading.Thread(target=pump, args=(conn,), daemon=True).start()

    threading.Thread(target=echo_loop, daemon=True).start()
    proxy = FaultProxy(srv.getsockname())
    rule = proxy.add_rule(FaultRule(action="drop", nth=2))
    try:
        outcomes = []
        for i in range(5):
            sk = socket.create_connection(proxy.addr, timeout=5.0)
            try:
                sk.sendall(b"ping")
                sk.settimeout(2.0)
                outcomes.append(sk.recv(4) == b"ping")
            except OSError:
                outcomes.append(False)
            finally:
                sk.close()
        # exactly the 3rd (0-based nth=2) connection died
        assert outcomes == [True, True, False, True, True], outcomes
        assert rule.expired and rule.hits == 1
        assert proxy.dropped == 1
    finally:
        stop.set()
        proxy.close()
        srv.close()


def test_one_shot_nth_rule_kills_admit_handshake_specifically():
    """Target the rejoin/admit handshake: after a partition, the FIRST
    redial carries the admit rendezvous — nth selects exactly it (the
    client's earlier setup dials already consumed indices 0 and 1, which
    conn=/max_conns= rules would need to predict). The client's backoff
    absorbs the kill and the admission still lands exactly once."""
    svc = ParamService(_zeros64(), n_workers=1, liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    # heartbeats off: the only post-sever connection is join()'s redial,
    # so the accepted-connection order is fully deterministic
    opts = dict(FAST, heartbeat_s=0.0)
    cli1 = AsyncSSPClient(1, proxy.addr, staleness=1, n_workers=1, **opts)
    try:
        rule = proxy.add_rule(FaultRule(action="drop", nth=0))
        # rule armed AFTER setup: nth counts from here — the next dial IS
        # the admit handshake's reconnect
        assert proxy.sever_all() == 2
        cache, _ = cli1.join()   # pull channel dead -> redial (killed once)
        assert rule.expired and proxy.dropped == 1
        assert svc.admissions == 1          # exactly once, despite the kill
        assert svc.members == {0, 1}
        assert cli1.clock == -1             # fresh job: anchor clock -1
        cli1.push(_delta(1, 0))
        cli1._drain()
        assert svc.applied_seq[1] == 0
    finally:
        cli1.close()
        proxy.close()
        svc.close()


# --------------------------------------------------------------------------- #
# THE acceptance scenario: 1 -> 3 -> 2 under chaos
# --------------------------------------------------------------------------- #

def test_chaos_scale_1_3_2_exactly_once_with_fixed_membership_replay():
    """Scale a live async-SSP job 1 -> 3 -> 2 through the FaultProxy, with
    a one-shot nth kill of worker 2's first dial and a full mid-run
    partition (sever_all) thrown in. Acceptance properties, all pinned
    bit-exactly because every (worker, clock) delta is a distinct power
    of two:

    - every clock applied exactly once (the anchor sum IS the applied
      set; a dup or drop changes it);
    - no SSP gate deadlock across admissions and the retirement (every
      gate completes within its timeout, and the post-shrink gates that
      worker 1's frozen clock WOULD have violated unblock);
    - loss continuity: each worker's per-clock losses are the expected
      unbroken sequence across every membership change;
    - final params identical to a fixed-membership (3-worker) service fed
      the same dispatched step sequence — bitwise."""
    s = 2
    svc = ParamService(_zeros64(), n_workers=1, liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    losses = {0: [], 1: [], 2: []}
    clis = {}

    def step(w, cli, c):
        """One clock for worker w: gate, 'train' (record the loss), push
        the (w, c) increment. Returns the gate wait."""
        waited = cli.gate(c, timeout_s=20.0)
        losses[w].append(float(c))     # deterministic 'loss' = the clock
        cli.push(_delta(w, c))
        return waited

    try:
        # ---- phase 1: one worker, clocks 0..3 --------------------------- #
        clis[0] = AsyncSSPClient(0, proxy.addr, staleness=s, n_workers=1,
                                 **FAST)
        for c in range(4):
            step(0, clis[0], c)
        clis[0]._drain()

        # ---- scale up 1 -> 3: admit w1 then w2 -------------------------- #
        clis[1] = AsyncSSPClient(1, proxy.addr, staleness=s, n_workers=1,
                                 **FAST)
        cache1, _ = clis[1].join()
        assert clis[1].clock == 3                 # the anchor clock
        np.testing.assert_array_equal(
            cache1["fc"]["w"], _expected([(0, c) for c in range(4)]))

        # chaos: kill w2's very first dial (its next accepted connection)
        kill = proxy.add_rule(FaultRule(action="drop", nth=0))
        clis[2] = AsyncSSPClient(2, proxy.addr, staleness=s, n_workers=1,
                                 **FAST)
        cache2, _ = clis[2].join()
        assert kill.expired and proxy.dropped >= 1
        assert clis[2].clock == 3
        assert svc.admissions == 2
        assert svc.members == {0, 1, 2}

        # ---- phase 2: three workers, clocks 4..6 ------------------------ #
        for c in range(4, 7):
            for w in (0, 1, 2):
                step(w, clis[w], c)
            if c == 5:
                # chaos: full mid-run partition; every channel reconnects
                # and replays, the seq dedup keeps the applied set exact
                proxy.sever_all()

        # ---- scale down 3 -> 2: w1 departs deliberately ----------------- #
        clis[1].leave()
        assert svc.retired == {1}
        assert svc.members == {0, 2}

        # ---- phase 3: two workers, clocks 7..11 ------------------------- #
        # w1 froze at clock 6; by clock 10 (> 6 + s + 1) its slot would
        # deadlock every gate were it still a member
        for c in range(7, 12):
            for w in (0, 2):
                waited = step(w, clis[w], c)
                assert waited < 15.0
        clis[0].mark_done()
        clis[2].mark_done()

        # ---- acceptance: exactly-once, spread bound, loss continuity ---- #
        applied = ([(0, c) for c in range(12)]
                   + [(1, c) for c in range(4, 7)]
                   + [(2, c) for c in range(4, 12)])
        np.testing.assert_array_equal(svc.anchor["fc"]["w"],
                                      _expected(applied))
        assert svc.max_spread <= s + 1
        assert losses[0] == [float(c) for c in range(12)]
        assert losses[1] == [4.0, 5.0, 6.0]
        assert losses[2] == [float(c) for c in range(4, 12)]
        done, failed = clis[0].wait_all_done(None, timeout_s=10.0)
        assert done == {0, 2} and not failed

        # ---- fixed-membership replay of the same dispatched sequence ---- #
        svc2 = ParamService(_zeros64(), n_workers=3, liveness_timeout_s=0.0)
        replay = {w: AsyncSSPClient(w, ("127.0.0.1", svc2.port), staleness=s,
                                    n_workers=3, **FAST) for w in (0, 1, 2)}
        try:
            for w, cli in replay.items():
                start = {0: 0, 1: 4, 2: 4}[w]
                cli.clock = start - 1
                cli._acked_clock = start - 1
                end = {0: 12, 1: 7, 2: 12}[w]
                for c in range(start, end):
                    cli.push(_delta(w, c))
                cli._drain()
            np.testing.assert_array_equal(svc2.anchor["fc"]["w"],
                                          svc.anchor["fc"]["w"])
        finally:
            for cli in replay.values():
                cli.close()
            svc2.close()
    finally:
        for cli in clis.values():
            cli.close()
        proxy.close()
        svc.close()


def test_worker_driver_join_and_retire_modes():
    """run_async_ssp_worker's elastic modes: join=True rendezvous via
    admit and trains from join_clock + 1; retire_at_clock scales down
    cleanly (drain + retire, survivors keep training)."""
    svc = ParamService(_zeros64(), n_workers=1, liveness_timeout_s=0.0)
    opts = dict(heartbeat_s=0.1, reconnect_deadline_s=5.0,
                backoff_base_s=0.01, backoff_cap_s=0.05)

    def local_step(w):
        def f(cache, it):
            out = {l: {p: v + _delta(w, it % 16)[l][p] for p, v in
                       ps.items()} for l, ps in cache.items()}
            return out, float(it)
        return f

    cli0 = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=4,
                          n_workers=1, **opts)
    try:
        for c in range(3):
            cli0.gate(c, timeout_s=10.0)
            cli0.push(_delta(0, c))
        cli0._drain()

        out = run_async_ssp_worker(
            1, 1, _zeros64(), local_step(1), n_clocks=7, staleness=4,
            service_addr=("127.0.0.1", svc.port), join=True,
            retire_at_clock=5, client_opts=opts)
        # joined at anchor clock 2 -> trained clocks 3..5, then retired
        assert out["start_clock"] == 3
        assert out["retired"] is True
        assert out["losses"] == [3.0, 4.0, 5.0]
        assert svc.retired == {1} and svc.members == {0}
        np.testing.assert_array_equal(
            svc.anchor["fc"]["w"],
            _expected([(0, 0), (0, 1), (0, 2),
                       (1, 3), (1, 4), (1, 5)]))
        cli0.mark_done()
    finally:
        cli0.close()
        svc.close()


# --------------------------------------------------------------------------- #
# resharded data assignment: keyed by (member list, epoch)
# --------------------------------------------------------------------------- #

def test_elastic_shard_partitions_cleanly_across_1_3_2():
    """For every membership of a 1 -> 3 -> 2 scale sequence the shards are
    disjoint and cover [0, n); the epoch permutation is shared (keyed by
    epoch, membership-independent), so a scale event re-cuts the SAME
    permutation into the new number of ranges."""
    n = 101
    for members in ([0], [0, 1, 2], [0, 2]):
        for epoch in (0, 3):
            parts = [elastic_shard_indices(n, w, members, epoch=epoch)
                     for w in members]
            flat = np.concatenate(parts)
            assert len(flat) == n
            assert set(flat.tolist()) == set(range(n)), \
                f"members={members} epoch={epoch} does not cover [0, n)"
    # position-in-sorted-list mapping: worker 2 is the SECOND of {0, 2}
    assert member_shard([0, 2], 2) == Shard(1, 2)
    assert member_shard([0, 1, 2], 1) == Shard(1, 3)
    # membership sets (not launch ranks) key the cut: {5, 9} works too
    assert member_shard({9, 5}, 9) == Shard(1, 2)
    with pytest.raises(ValueError):
        member_shard([0, 2], 1)
    # epoch keying: different epochs permute differently, same cover
    e0 = elastic_shard_indices(n, 0, [0, 1], epoch=0)
    e1 = elastic_shard_indices(n, 0, [0, 1], epoch=1)
    assert not np.array_equal(e0, e1)


# --------------------------------------------------------------------------- #
# membership telemetry export
# --------------------------------------------------------------------------- #

def test_membership_counters_export_and_format():
    """ParamService churn counters surface through comm_stats (the
    engine's display + stats.yaml path) — no log-grepping required."""
    from poseidon_tpu.runtime.comm_stats import (format_membership,
                                                 membership_counters)

    svc = ParamService(_zeros64(), n_workers=1, liveness_timeout_s=0.0)
    cli1 = AsyncSSPClient(1, ("127.0.0.1", svc.port), staleness=0,
                          n_workers=1, **FAST)
    try:
        cli1.join()
        c = membership_counters(service=svc)
        assert c["admissions"] == 1.0
        assert c["members"] == 2.0
        assert c["evictions"] == 0.0 and c["rejoins"] == 0.0
        assert c["retired"] == 0.0
        line = format_membership(c)
        assert "admissions = 1" in line and "members = 2" in line

        cli1.leave()
        c = membership_counters(service=svc)
        assert c["members"] == 1.0 and c["retired"] == 1.0

        # client-side view (every non-zero rank)
        cc = membership_counters(client=cli1)
        assert cc["members"] == 1.0 and "reconnects" in cc
    finally:
        cli1.close()
        svc.close()


# --------------------------------------------------------------------------- #
# engine/tier integration (jax, CPU)
# --------------------------------------------------------------------------- #

_SMALLNET = """
name: "ElasticNet"
layers { name: "src" type: MEMORY_DATA top: "data" top: "label"
  memory_data_param { batch_size: 8 channels: 1 height: 12 width: 12 } }
layers { name: "ip1" type: INNER_PRODUCT bottom: "data" top: "ip1"
  inner_product_param { num_output: 5
    weight_filler { type: "xavier" } bias_filler { type: "constant" } } }
layers { name: "loss" type: SOFTMAX_LOSS bottom: "ip1" bottom: "label"
  top: "loss" }
"""


def _memory_data(n=64, seed=0):
    rs = np.random.RandomState(seed)
    return {"data": rs.randn(n, 1, 12, 12).astype(np.float32),
            "label": rs.randint(0, 5, n)}


def _small_engine(tmp_path, **kw):
    from poseidon_tpu.proto.messages import (SolverParameter,
                                             load_net_from_string)
    from poseidon_tpu.runtime.engine import Engine
    sp = SolverParameter(train_net_param=load_net_from_string(_SMALLNET),
                         base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         display=0, max_iter=kw.pop("max_iter", 4),
                         random_seed=3)
    return Engine(sp, memory_data=_memory_data(),
                  output_dir=str(tmp_path), **kw)


def test_engine_reshard_data_rebuilds_pipelines(tmp_path):
    """reshard_data re-keys the TRAIN assignment mid-run: pipelines are
    rebuilt against the new contiguous range and training keeps going."""
    eng = _small_engine(tmp_path, max_iter=2)
    try:
        eng.train()
        old_pipes = list(eng.train_pipelines)
        assert eng._data_shard == Shard(0, 1)
        assert eng.reshard_data(Shard(0, 2)) is True
        assert eng._data_shard == Shard(0, 2)
        assert eng.train_pipelines[0] is not old_pipes[0]
        assert eng.reshard_data(Shard(0, 2)) is False   # no-op on same
        eng.train(max_iter=4)   # two more iterations on the new shard
        assert eng.iteration() == 4
    finally:
        eng.close()


def test_tier_membership_change_reshards_engine(tmp_path, monkeypatch):
    """The product seam: an admission lands, and the NEXT flush boundary
    reshards the engine's data assignment by the grown member list."""
    import types

    from poseidon_tpu.runtime.async_tier import AsyncSSPTier

    monkeypatch.setenv("POSEIDON_PROC_ID", "0")
    monkeypatch.setenv("POSEIDON_NUM_PROCS", "1")
    monkeypatch.delenv("POSEIDON_COORDINATOR", raising=False)

    params = _zeros64()
    resharded = []
    eng = types.SimpleNamespace()
    eng.params = params
    eng.train_step = types.SimpleNamespace(replicated=None)
    eng.reshard_data = lambda shard: resharded.append(shard)

    tier = AsyncSSPTier(params, staleness=50, service_port=0)
    joiner = None
    try:
        assert tier.data_shard() == Shard(0, 1)
        # a new worker joins the live job
        joiner = AsyncSSPClient(1, ("127.0.0.1", tier.service.port),
                                staleness=50, n_workers=1, **FAST)
        joiner.join()
        assert tier.service.admissions == 1
        # next flush boundary: the tier folds the admission into the shard
        tier.after_iters(eng, 1)
        assert resharded and resharded[-1] == Shard(0, 2)
        assert tier.membership_counters()["admissions"] == 1.0
        # the joiner departs; the next boundary re-cuts back to one range
        joiner.leave()
        tier.after_iters(eng, 1)
        assert resharded[-1] == Shard(0, 1)
        tier.finish(eng)
    finally:
        if joiner is not None:
            joiner.close()
        if tier.service is not None:
            tier.service.close()


def test_joiner_tier_is_admitted_without_operator_action(monkeypatch):
    """A process launched with POSEIDON_PROC_ID >= POSEIDON_NUM_PROCS (the
    elastic-joiner env contract) builds its tier, is ADMITTED at the
    anchor clock, and computes its member-keyed data shard — no relaunch
    of the fleet, no new hostfile."""
    import types

    from poseidon_tpu.runtime.async_tier import AsyncSSPTier

    params = _zeros64()
    svc = ParamService(params, n_workers=2, liveness_timeout_s=0.0)
    cli0 = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=50,
                          n_workers=2, **FAST)
    tier = None
    try:
        cli0.push(_delta(0, 0))
        cli0._drain()

        monkeypatch.setenv("POSEIDON_PROC_ID", "2")
        monkeypatch.setenv("POSEIDON_NUM_PROCS", "2")
        monkeypatch.delenv("POSEIDON_COORDINATOR", raising=False)
        tier = AsyncSSPTier(params, staleness=50, service_port=svc.port)
        assert svc.admissions == 1
        assert svc.members == {0, 1, 2}
        # admitted at the anchor clock (min live = worker 1's -1)
        assert tier.client.clock == -1
        # the anchor seeded the joiner's cache
        np.testing.assert_array_equal(tier.resume_cache["fc"]["w"],
                                      _expected([(0, 0)]))
        assert tier.data_shard() == Shard(2, 3)

        eng = types.SimpleNamespace()
        eng.params = tier.resume_cache
        eng.train_step = types.SimpleNamespace(replicated=None)
        tier.after_iters(eng, 1)    # first flush from the admitted worker
        tier.client._drain()
        assert svc.applied_seq[2] == 0
    finally:
        if tier is not None:
            tier.client.close()
        cli0.close()
        svc.close()


# --------------------------------------------------------------------------- #
# fast restart: compile cache + AOT step store
# --------------------------------------------------------------------------- #

def test_compile_cache_enable_and_entries(tmp_path):
    import jax
    import jax.numpy as jnp

    from poseidon_tpu.runtime.compile_cache import (cache_entries,
                                                    disable_compile_cache,
                                                    enable_compile_cache)

    cache = enable_compile_cache(str(tmp_path / "cc"))
    try:
        assert jax.config.jax_compilation_cache_dir == cache
        before = cache_entries(cache)
        x = jnp.ones((16, 16))
        jax.block_until_ready(
            jax.jit(lambda a: jnp.tanh(a) @ a.T, donate_argnums=())(x))
        assert cache_entries(cache) > before, \
            "the persistent cache recorded no entry for a fresh compile"
    finally:
        # the cache config is process-global and tmp_path gets garbage-
        # collected: leaving it enabled made LATER tests' compiles
        # deserialize torn entries and abort the whole tier-1 run
        disable_compile_cache()


def test_step_key_stability_and_sensitivity():
    from poseidon_tpu.runtime.compile_cache import step_key

    a = step_key(model="lenet", batch={"data": ([8, 1, 12, 12], "float32")},
                 mesh={"data": 8}, backend="cpu")
    b = step_key(mesh={"data": 8}, backend="cpu", model="lenet",
                 batch={"data": ([8, 1, 12, 12], "float32")})
    assert a == b, "kwargs order must not change the key"
    c = step_key(model="lenet", batch={"data": ([16, 1, 12, 12], "float32")},
                 mesh={"data": 8}, backend="cpu")
    assert a != c, "a shape change must miss"


def test_aot_step_store_roundtrip_bitwise(tmp_path):
    """A serialized train-step executable reloads and produces BITWISE the
    jit path's outputs — the warm start changes when compilation happens,
    never what runs."""
    import jax

    from poseidon_tpu.core.net import Net
    from poseidon_tpu.parallel import (CommConfig, build_train_step,
                                       init_train_state, make_mesh)
    from poseidon_tpu.proto.messages import (SolverParameter,
                                             load_net_from_string)
    from poseidon_tpu.runtime.compile_cache import (load_step_executable,
                                                    save_step_executable,
                                                    step_key)

    shapes = {"data": (8, 1, 12, 12), "label": (8,)}
    net = Net(load_net_from_string(_SMALLNET), "TRAIN", source_shapes=shapes)
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    mesh = make_mesh()
    # donation off: the test calls BOTH the jit step and the reloaded
    # executable on the same (params, state) trees
    ts = build_train_step(net, sp, mesh, CommConfig(), donate=False)
    params = net.init(jax.random.PRNGKey(0))
    state = init_train_state(params, CommConfig(),
                             int(np.prod(list(mesh.shape.values()))))
    rs = np.random.RandomState(0)
    from jax.sharding import NamedSharding, PartitionSpec as P
    sh = NamedSharding(mesh, P("data"))
    batch = {"data": jax.device_put(
                 rs.randn(8, 1, 12, 12).astype(np.float32), sh),
             "label": jax.device_put(rs.randint(0, 5, 8), sh)}
    rng = jax.random.PRNGKey(7)

    cache = str(tmp_path / "cc")
    key = step_key(model="elastic_smallnet", backend=jax.default_backend())
    assert load_step_executable(cache, key) is None   # clean miss
    compiled = ts.lowerable.lower(params, state, batch, rng).compile()
    assert save_step_executable(cache, key, compiled) is not None
    loaded = load_step_executable(cache, key)
    assert loaded is not None

    p1, s1, m1 = ts.step(params, state, batch, rng)
    out = loaded(params, state, batch, rng)
    p2, s2, m2 = out[:3]
    np.testing.assert_array_equal(np.asarray(m1["loss"]),
                                  np.asarray(m2["loss"]))
    for l in p1:
        for p in p1[l]:
            np.testing.assert_array_equal(np.asarray(p1[l][p]),
                                          np.asarray(p2[l][p]))


def test_engine_aot_warm_start_loads_across_engines(tmp_path):
    """Two engine incarnations of the same config against one cache dir:
    the first compiles + serializes, the second LOADS (trace and compile
    skipped) and trains to bit-identical final params."""
    from poseidon_tpu import config
    from poseidon_tpu.runtime.compile_cache import (aot_entries,
                                                    disable_compile_cache,
                                                    enable_compile_cache)

    cache = enable_compile_cache(str(tmp_path / "cc"))
    config.set_compile_cache_config(cache_dir=cache, aot_steps=True)
    try:
        eng1 = _small_engine(tmp_path / "r1", max_iter=3)
        last1 = eng1.train()
        eng1.close()
        assert eng1._aot_exec is not None and not eng1._aot_failed
        assert aot_entries(cache) == 1

        eng2 = _small_engine(tmp_path / "r2", max_iter=3)
        last2 = eng2.train()
        eng2.close()
        assert eng2._aot_exec is not None
        assert aot_entries(cache) == 1    # loaded, not re-serialized
        assert last1["loss"] == last2["loss"]
    finally:
        config.set_compile_cache_config(cache_dir="", aot_steps=True)
        disable_compile_cache()
