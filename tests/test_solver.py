"""Solver update math vs hand-computed Caffe semantics."""

import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.proto.messages import SolverParameter
from poseidon_tpu.solvers.updates import (
    init_state, learning_rate, make_update_fn)


def _mults():
    return {"l": {"w": (1.0, 1.0)}}


def _pack(x):
    return {"l": {"w": jnp.asarray(x, jnp.float32)}}


def test_lr_policies():
    sp = SolverParameter(base_lr=0.1, lr_policy="step", gamma=0.5, stepsize=10)
    assert float(learning_rate(sp, jnp.asarray(0))) == pytest.approx(0.1)
    assert float(learning_rate(sp, jnp.asarray(9))) == pytest.approx(0.1)
    assert float(learning_rate(sp, jnp.asarray(10))) == pytest.approx(0.05)
    assert float(learning_rate(sp, jnp.asarray(25))) == pytest.approx(0.025)

    sp = SolverParameter(base_lr=0.1, lr_policy="inv", gamma=1e-4, power=0.75)
    assert float(learning_rate(sp, jnp.asarray(100))) == pytest.approx(
        0.1 * (1 + 1e-4 * 100) ** -0.75, rel=1e-5)

    sp = SolverParameter(base_lr=0.1, lr_policy="poly", power=2.0, max_iter=100)
    assert float(learning_rate(sp, jnp.asarray(50))) == pytest.approx(
        0.1 * 0.25, rel=1e-5)

    sp = SolverParameter(base_lr=0.1, lr_policy="exp", gamma=0.99)
    assert float(learning_rate(sp, jnp.asarray(10))) == pytest.approx(
        0.1 * 0.99 ** 10, rel=1e-5)

    sp = SolverParameter(base_lr=0.1, lr_policy="multistep", gamma=0.1,
                         stepvalue=[5, 8])
    assert float(learning_rate(sp, jnp.asarray(6))) == pytest.approx(0.01)
    assert float(learning_rate(sp, jnp.asarray(9))) == pytest.approx(0.001)


def test_sgd_momentum_weight_decay():
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.01, solver_type="SGD")
    update = make_update_fn(sp, _mults())
    w = np.array([1.0, -2.0], np.float32)
    g = np.array([0.5, 0.25], np.float32)
    params, state = _pack(w), init_state(_pack(w))
    params, state = update(params, _pack(g), state)
    # h = 0.9*0 + 0.1*(g + 0.01*w); w -= h
    h = 0.1 * (g + 0.01 * w)
    np.testing.assert_allclose(np.asarray(params["l"]["w"]), w - h, rtol=1e-6)
    # second step: momentum kicks in
    params, state = update(params, _pack(g), state)
    w1 = w - h
    h2 = 0.9 * h + 0.1 * (g + 0.01 * w1)
    np.testing.assert_allclose(np.asarray(params["l"]["w"]), w1 - h2, rtol=1e-6)


def test_sgd_l1_regularization():
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", momentum=0.0,
                         weight_decay=0.01, regularization_type="L1")
    update = make_update_fn(sp, _mults())
    w = np.array([1.0, -2.0, 0.0], np.float32)
    g = np.zeros(3, np.float32)
    params, state = update(_pack(w), _pack(g), init_state(_pack(w)))
    expect = w - 0.1 * 0.01 * np.sign(w)
    np.testing.assert_allclose(np.asarray(params["l"]["w"]), expect, rtol=1e-6)


def test_nesterov():
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", momentum=0.9,
                         solver_type="NESTEROV")
    update = make_update_fn(sp, _mults())
    w = np.array([1.0], np.float32)
    g = np.array([1.0], np.float32)
    params, state = update(_pack(w), _pack(g), init_state(_pack(w)))
    # h' = 0.1; step = 1.9*0.1 - 0.9*0 = 0.19
    np.testing.assert_allclose(np.asarray(params["l"]["w"]), [1.0 - 0.19],
                               rtol=1e-6)


def test_adagrad():
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", solver_type="ADAGRAD",
                         delta=1e-8)
    update = make_update_fn(sp, _mults())
    w = np.array([1.0], np.float32)
    g = np.array([2.0], np.float32)
    params, state = update(_pack(w), _pack(g), init_state(_pack(w)))
    # h = 4; step = 0.1 * 2 / (2 + 1e-8) = 0.1
    np.testing.assert_allclose(np.asarray(params["l"]["w"]), [0.9], rtol=1e-5)
    params, state = update(params, _pack(g), state)
    # h = 8; step = 0.1*2/sqrt(8)
    np.testing.assert_allclose(np.asarray(params["l"]["w"]),
                               [0.9 - 0.2 / np.sqrt(8)], rtol=1e-5)


def test_lr_mult_decay_mult():
    sp = SolverParameter(base_lr=0.1, lr_policy="fixed", weight_decay=0.01)
    mults = {"l": {"w": (2.0, 0.0)}}
    update = make_update_fn(sp, mults)
    w = np.array([1.0], np.float32)
    g = np.array([1.0], np.float32)
    params, _ = update(_pack(w), _pack(g), init_state(_pack(w)))
    # lr doubled, decay zeroed
    np.testing.assert_allclose(np.asarray(params["l"]["w"]), [1.0 - 0.2],
                               rtol=1e-6)
