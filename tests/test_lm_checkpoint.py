"""LM checkpoints: canonical layout on disk, cross-topology resume."""

import jax
import numpy as np

from poseidon_tpu.models.transformer import (
    TransformerConfig, build_dp_pp_train_step, forward, init_params, lm_loss,
    to_pp_layout, to_tp_layout, transformer_mults)
from poseidon_tpu.parallel.mesh import make_mesh
from poseidon_tpu.proto.messages import SolverParameter
from poseidon_tpu.runtime.lm_checkpoint import (
    latest_lm_snapshot, restore_lm, save_lm)
from poseidon_tpu.solvers.updates import init_state, make_update_fn

from conftest import pattern_batch

CFG = TransformerConfig(vocab_size=32, d_model=64, n_heads=2, n_layers=2,
                        d_ff=128, max_seq=64)
B, S = 8, 32


def _batch(rs, b, s):
    return pattern_batch(rs, b, s, CFG.vocab_size)


def test_cross_topology_resume_matches_uninterrupted_run(tmp_path):
    """Two steps on the 3-D (data x stage x model) mesh, snapshot in
    canonical layout, resume SINGLE-DEVICE for a third step — must equal
    three uninterrupted single-device steps (momentum history included).
    This is the LM analog of the CNN path's cross-mode coerce_state."""
    sp = SolverParameter(base_lr=0.05, lr_policy="fixed", momentum=0.9)
    params0 = init_params(CFG, jax.random.PRNGKey(0))
    rs = np.random.RandomState(1)
    tokens, targets = _batch(rs, B, S)

    # interrupted path: 2 steps under 3-D parallelism
    mesh3d = make_mesh(axes=("data", "stage", "model"), shape=(2, 2, 2))
    p3d = to_pp_layout(to_tp_layout(params0, CFG), CFG)
    step3d = build_dp_pp_train_step(CFG, sp, mesh3d, p3d, microbatches=2,
                                    tp_axis="model", donate=False)
    st = init_state(p3d)
    p = p3d
    for it in range(2):
        p, st, _ = step3d(p, st, tokens, targets, jax.random.PRNGKey(it))
    path = save_lm(str(tmp_path / "lm"), p, st, CFG, layout=("tp", "pp"))
    assert latest_lm_snapshot(str(tmp_path / "lm")) == path

    p_res, st_res = restore_lm(path, CFG)  # canonical: single-device
    assert int(st_res.it) == 2
    upd = make_update_fn(sp, transformer_mults(p_res))

    def one_step(params, state):
        loss, grads = jax.value_and_grad(
            lambda q: lm_loss(forward(q, CFG, tokens), targets))(params)
        return upd(params, grads, state)

    p_final, _ = one_step(p_res, st_res)

    # reference: 3 uninterrupted single-device steps
    p_ref, st_ref = params0, init_state(params0)
    for _ in range(3):
        p_ref, st_ref = one_step(p_ref, st_ref)

    for lname in p_ref:
        for k in p_ref[lname]:
            np.testing.assert_allclose(
                np.asarray(p_final[lname][k]), np.asarray(p_ref[lname][k]),
                rtol=5e-3, atol=5e-5, err_msg=f"{lname}/{k}")


def test_restore_into_other_layout_roundtrips(tmp_path):
    """Saving from one layout and restoring into another applies the
    target layout exactly (spot-check: tp restore of a plain save)."""
    from poseidon_tpu.models.transformer import from_tp_layout
    params = init_params(CFG, jax.random.PRNGKey(2))
    st = init_state(params)
    path = save_lm(str(tmp_path / "lm2"), params, st, CFG, layout=())
    p_tp, st_tp = restore_lm(path, CFG, layout=("tp",))
    back = from_tp_layout(p_tp, CFG)
    for lname in params:
        for k in params[lname]:
            np.testing.assert_array_equal(np.asarray(back[lname][k]),
                                          np.asarray(params[lname][k]))
    assert int(st_tp.it) == 0
