"""Golden-value numerics tests: XLA ops vs naive-numpy Caffe semantics."""

import numpy as np
import pytest

import caffe_ref as ref
from poseidon_tpu.ops import elementwise as E
from poseidon_tpu.ops import losses as L
from poseidon_tpu.ops import nn as NN


@pytest.mark.parametrize("k,s,p,h", [
    (2, 2, 0, 8), (3, 2, 0, 7), (3, 2, 1, 8), (5, 3, 2, 13), (3, 1, 1, 6),
])
def test_max_pool_matches_caffe(rng_np, k, s, p, h):
    x = rng_np.randn(2, 3, h, h).astype(np.float32)
    got = np.asarray(NN.max_pool(x, (k, k), (s, s), (p, p)))
    want = ref.max_pool(x, k, s, p)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-6)


@pytest.mark.parametrize("k,s,p,h", [
    (2, 2, 0, 8), (3, 2, 0, 7), (3, 2, 1, 8), (5, 3, 2, 13), (3, 1, 1, 6),
])
def test_ave_pool_matches_caffe(rng_np, k, s, p, h):
    x = rng_np.randn(2, 3, h, h).astype(np.float32)
    got = np.asarray(NN.ave_pool(x, (k, k), (s, s), (p, p)))
    want = ref.ave_pool(x, k, s, p)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("group", [1, 2])
def test_conv_matches_caffe(rng_np, group):
    x = rng_np.randn(2, 4, 9, 9).astype(np.float32)
    w = rng_np.randn(6, 4 // group, 3, 3).astype(np.float32)
    b = rng_np.randn(6).astype(np.float32)
    got = np.asarray(NN.conv2d(x, w, b, (2, 2), (1, 1), group))
    want = ref.conv2d(x, w, b, 2, 1, group)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("group", [1, 2])
def test_conv_nhwc_layout_matches_nchw(rng_np, group):
    """Native NHWC (TPU-preferred) conv: channels-last activations with
    the SAME canonical OIHW weight, same numbers, forward and backward
    (the net-level layout plan's per-op contract)."""
    import jax
    x = rng_np.randn(2, 4, 9, 9).astype(np.float32)
    xt = np.transpose(x, (0, 2, 3, 1)).copy()
    w = rng_np.randn(6, 4 // group, 3, 3).astype(np.float32)
    b = rng_np.randn(6).astype(np.float32)

    def loss_nchw(args, *, _g=group):
        xx, ww, bb = args
        return NN.conv2d(xx, ww, bb, (2, 2), (1, 1), _g).sum()

    def loss_nhwc(args, *, _g=group):
        xx, ww, bb = args
        return NN.conv2d(xx, ww, bb, (2, 2), (1, 1), _g,
                         layout="NHWC").sum()

    y1 = np.asarray(NN.conv2d(x, w, b, (2, 2), (1, 1), group))
    g1 = jax.grad(loss_nchw)((x, w, b))
    y2 = np.asarray(NN.conv2d(xt, w, b, (2, 2), (1, 1), group,
                              layout="NHWC"))
    g2 = jax.grad(loss_nhwc)((xt, w, b))
    np.testing.assert_allclose(y1, np.transpose(y2, (0, 3, 1, 2)),
                               rtol=1e-5, atol=1e-5)
    gx1, gw1, gb1 = g1
    gx2, gw2, gb2 = g2
    np.testing.assert_allclose(np.asarray(gx1),
                               np.transpose(np.asarray(gx2), (0, 3, 1, 2)),
                               rtol=1e-4, atol=1e-5, err_msg="x")
    # weight/bias grads are CANONICAL in either layout — the whole point
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2),
                               rtol=1e-4, atol=1e-5, err_msg="w")
    np.testing.assert_allclose(np.asarray(gb1), np.asarray(gb2),
                               rtol=1e-4, atol=1e-5, err_msg="b")


def test_lrn_across_channels(rng_np):
    x = rng_np.randn(2, 8, 5, 5).astype(np.float32)
    got = np.asarray(NN.lrn_across_channels(x, 5, 1e-4, 0.75))
    want = ref.lrn_across(x, 5, 1e-4, 0.75)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_pool_lrn_nhwc_layout_matches_nchw(rng_np):
    """Native channels-last pooling/LRN/stochastic-pool (the net-level
    NHWC plan runs these with zero boundary transposes — round 3's per-op
    shim left pool/LRN NCHW and every transpose survived, the 1.9x
    anomaly): identical numbers either way, forward and backward."""
    import jax
    x = rng_np.randn(2, 8, 9, 9).astype(np.float32)
    xt = np.transpose(x, (0, 2, 3, 1)).copy()
    xpos = np.abs(x) + 0.1
    xpos_t = np.transpose(xpos, (0, 2, 3, 1)).copy()

    fns = {
        "max": lambda a, lay: NN.max_pool(a, (3, 3), (2, 2), (1, 1), lay),
        "ave": lambda a, lay: NN.ave_pool(a, (3, 3), (2, 2), (1, 1), lay),
        "lrn": lambda a, lay: NN.lrn_across_channels(a, 5, 1e-4, 0.75,
                                                     1.0, lay),
        "lrn_w": lambda a, lay: NN.lrn_within_channel(a, 3, 1e-4, 0.75,
                                                      lay),
        "gap": lambda a, lay: NN.global_ave_pool(a, lay),
    }
    for k, f in fns.items():
        o1 = np.asarray(f(x, "NCHW"))
        o2 = np.asarray(f(xt, "NHWC"))
        np.testing.assert_allclose(o1, np.transpose(o2, (0, 3, 1, 2)),
                                   rtol=1e-5, atol=1e-6, err_msg=k)
    sp1 = np.asarray(NN.stochastic_pool(xpos, (3, 3), (3, 3), (0, 0),
                                        None, True, "NCHW"))
    sp2 = np.asarray(NN.stochastic_pool(xpos_t, (3, 3), (3, 3), (0, 0),
                                        None, True, "NHWC"))
    np.testing.assert_allclose(sp1, np.transpose(sp2, (0, 3, 1, 2)),
                               rtol=1e-5, atol=1e-6, err_msg="stochastic")
    for k in ("max", "lrn"):
        g1 = jax.grad(lambda a, _f=fns[k]: _f(a, "NCHW").sum())(x)
        g2 = jax.grad(lambda a, _f=fns[k]: _f(a, "NHWC").sum())(xt)
        np.testing.assert_allclose(
            np.asarray(g1), np.transpose(np.asarray(g2), (0, 3, 1, 2)),
            rtol=1e-5, atol=1e-6, err_msg=f"grad:{k}")


def test_lrn_within_channel(rng_np):
    x = rng_np.randn(2, 3, 7, 7).astype(np.float32)
    got = np.asarray(NN.lrn_within_channel(x, 3, 5e-5, 0.75))
    want = ref.lrn_within(x, 3, 5e-5, 0.75)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_softmax_loss_matches_caffe(rng_np):
    logits = rng_np.randn(4, 10).astype(np.float32)
    labels = rng_np.randint(0, 10, size=(4,))
    got = float(L.softmax_loss(logits, labels))
    want = ref.softmax_loss(logits, labels)
    assert got == pytest.approx(want, rel=1e-5)


def test_softmax_loss_spatial(rng_np):
    logits = rng_np.randn(2, 5, 3, 3).astype(np.float32)
    labels = rng_np.randint(0, 5, size=(2, 3, 3))
    got = float(L.softmax_loss(logits, labels))
    want = ref.softmax_loss(logits, labels)
    assert got == pytest.approx(want, rel=1e-5)


def test_euclidean_loss(rng_np):
    a = rng_np.randn(4, 3).astype(np.float32)
    b = rng_np.randn(4, 3).astype(np.float32)
    assert float(L.euclidean_loss(a, b)) == pytest.approx(
        ((a - b) ** 2).sum() / 8.0, rel=1e-6)


def test_hinge_loss(rng_np):
    s = rng_np.randn(3, 5).astype(np.float32)
    y = np.array([1, 0, 4])
    m = s.copy()
    m[np.arange(3), y] *= -1
    m = np.maximum(0, 1 + m)
    assert float(L.hinge_loss(s, y, "L1")) == pytest.approx(m.sum() / 3, rel=1e-6)
    assert float(L.hinge_loss(s, y, "L2")) == pytest.approx(
        (m * m).sum() / 3, rel=1e-6)


def test_accuracy_topk(rng_np):
    s = np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1]], np.float32)
    y = np.array([1, 2])
    assert float(L.accuracy(s, y, 1)) == pytest.approx(0.5)
    assert float(L.accuracy(s, y, 2)) == pytest.approx(0.5)
    assert float(L.accuracy(s, y, 3)) == pytest.approx(1.0)


def test_sigmoid_ce(rng_np):
    x = rng_np.randn(3, 4).astype(np.float32)
    t = rng_np.rand(3, 4).astype(np.float32)
    want = (np.maximum(x, 0) - x * t + np.log1p(np.exp(-np.abs(x)))).sum() / 3
    assert float(L.sigmoid_cross_entropy_loss(x, t)) == pytest.approx(want, rel=1e-5)


def test_contrastive_loss(rng_np):
    a = rng_np.randn(4, 6).astype(np.float32)
    b = rng_np.randn(4, 6).astype(np.float32)
    y = np.array([1, 0, 1, 0], np.float32)
    d2 = ((a - b) ** 2).sum(1)
    want = (np.where(y > 0, d2, np.maximum(1.0 - d2, 0))).sum() / 8
    assert float(L.contrastive_loss(a, b, y, 1.0)) == pytest.approx(want, rel=1e-5)


def test_bnll_power_threshold(rng_np):
    x = rng_np.randn(3, 4).astype(np.float32) * 3
    np.testing.assert_allclose(
        np.asarray(E.bnll(x)), np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0),
        rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(E.power(x, 2.0, 0.5, 1.0)), (1.0 + 0.5 * x) ** 2, rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(E.threshold(x, 0.5)), (x > 0.5).astype(np.float32))


def test_mvn(rng_np):
    x = rng_np.randn(2, 3, 4, 4).astype(np.float32)
    got = np.asarray(E.mvn(x, True, False))
    for i in range(2):
        for c in range(3):
            sl = x[i, c]
            want = (sl - sl.mean()) / (np.sqrt((sl ** 2).mean() - sl.mean() ** 2) + 1e-10)
            np.testing.assert_allclose(got[i, c], want, rtol=1e-4, atol=1e-5)


def test_eltwise_and_slice(rng_np):
    a = rng_np.randn(2, 4).astype(np.float32)
    b = rng_np.randn(2, 4).astype(np.float32)
    np.testing.assert_allclose(np.asarray(E.eltwise([a, b], "SUM", [2.0, -1.0])),
                               2 * a - b, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(E.eltwise([a, b], "MAX", [])),
                               np.maximum(a, b))
    parts = E.slice_blob(a, 1, [1, 3], 3)
    assert [p.shape[1] for p in parts] == [1, 2, 1]


def test_im2col_shape(rng_np):
    x = rng_np.randn(2, 3, 8, 8).astype(np.float32)
    out = np.asarray(NN.im2col(x, (3, 3), (2, 2), (1, 1)))
    assert out.shape == (2, 27, 4, 4)


def test_dropout_scaling(rng_np):
    import jax
    x = np.ones((1000,), np.float32)
    y = np.asarray(E.dropout(x, 0.4, jax.random.PRNGKey(0), True))
    kept = y[y > 0]
    np.testing.assert_allclose(kept, 1.0 / 0.6, rtol=1e-5)
    assert abs(len(kept) / 1000 - 0.6) < 0.08
    np.testing.assert_allclose(np.asarray(E.dropout(x, 0.4, None, False)), x)


@pytest.mark.parametrize("c,k,s,p,h", [
    (3, 11, 4, 0, 227),   # AlexNet conv1
    (3, 7, 2, 3, 49),     # GoogLeNet conv1 shape family (reduced spatial)
    (1, 5, 2, 1, 17),     # k not divisible by s, odd sizes
    (4, 4, 4, 2, 19),     # k == s with padding
])
def test_conv_space_to_depth_exact(rng_np, c, k, s, p, h):
    """The s2d stem rewrite is the identical sum re-bracketed: forward and
    backward must match the direct conv to float tolerance."""
    import jax
    from poseidon_tpu.config import policy_scope
    x = rng_np.randn(2, c, h, h).astype(np.float32)
    w = rng_np.randn(8, c, k, k).astype(np.float32)
    b = rng_np.randn(8).astype(np.float32)

    def loss(args):
        xx, ww, bb = args
        return (NN.conv2d(xx, ww, bb, (s, s), (p, p), 1) ** 2).sum()

    y1 = np.asarray(NN.conv2d(x, w, b, (s, s), (p, p), 1))
    g1 = jax.grad(loss)((x, w, b))
    with policy_scope(conv_s2d=True):
        y2 = np.asarray(NN.conv2d(x, w, b, (s, s), (p, p), 1))
        g2 = jax.grad(loss)((x, w, b))
    assert y1.shape == y2.shape
    np.testing.assert_allclose(y2, y1, rtol=1e-5, atol=5e-5)
    # grads re-bracket ~k*k*O-term float sums; tolerance covers order noise
    for a, c_, name in zip(g1, g2, "xwb"):
        np.testing.assert_allclose(np.asarray(c_), np.asarray(a),
                                   rtol=1e-3, atol=3e-4, err_msg=name)


def test_s2d_real_stems_parity_and_perf_config_default(rng_np):
    """The bf16 perf config (numeric.set_perf_policy — what bench.py and
    ``train --bf16`` run) flips conv_s2d ON; this pins the rewrite at the
    REAL stem configurations. f32 parity is checked at float-sum-rebracket
    tolerance against the direct conv1 formulation for both stems:
    AlexNet conv1 (96x3x11x11 / s4 / p0 @ 227) and GoogLeNet conv1
    (64x3x7x7 / s2 / p3 @ 224)."""
    import jax.numpy as jnp
    from poseidon_tpu import config
    from poseidon_tpu.config import policy_scope

    # the perf config's defaults, restored by hand (set_perf_policy has no
    # scope form — it is the bench/CLI entry point)
    saved = (config.policy().compute_dtype, config.policy().conv_s2d)
    try:
        config.set_perf_policy()
        assert config.policy().compute_dtype == jnp.bfloat16
        assert config.policy().conv_s2d is True
    finally:
        config.set_policy(compute_dtype=saved[0], conv_s2d=saved[1])

    stems = [
        ("alexnet_conv1", 96, 11, 4, 0, 227),
        ("googlenet_conv1", 64, 7, 2, 3, 224),
    ]
    for name, o, k, s, p, h in stems:
        x = rng_np.randn(1, 3, h, h).astype(np.float32)
        w = (rng_np.randn(o, 3, k, k).astype(np.float32) / k)
        b = rng_np.randn(o).astype(np.float32)
        y_direct = np.asarray(NN.conv2d(x, w, b, (s, s), (p, p), 1))
        with policy_scope(conv_s2d=True):
            y_s2d = np.asarray(NN.conv2d(x, w, b, (s, s), (p, p), 1))
        assert y_direct.shape == y_s2d.shape, name
        np.testing.assert_allclose(y_s2d, y_direct, rtol=1e-5, atol=1e-5,
                                   err_msg=name)


def test_conv_space_to_depth_skips_many_channel_convs(rng_np):
    """The rewrite must only fire on lane-starved stems (C <= 4)."""
    import jax.numpy as jnp
    from poseidon_tpu.ops.nn import _s2d_applicable
    from poseidon_tpu.config import policy_scope
    x8 = jnp.zeros((1, 8, 9, 9))
    x3 = jnp.zeros((1, 3, 9, 9))
    w8 = jnp.zeros((4, 8, 3, 3))
    w3 = jnp.zeros((4, 3, 3, 3))
    with policy_scope(conv_s2d=True):
        assert not _s2d_applicable(x8, w8, (2, 2), 1, "NCHW")  # enough lanes
        assert not _s2d_applicable(x3, w3, (1, 1), 1, "NCHW")  # stride 1
        assert not _s2d_applicable(x3, w3, (2, 2), 3, "NCHW")  # grouped
        assert _s2d_applicable(x3, w3, (2, 2), 1, "NCHW")
        # NHWC: the channel count is read off the minor axis
        import jax.numpy as _jnp
        assert _s2d_applicable(_jnp.zeros((1, 9, 9, 3)), w3, (2, 2), 1,
                               "NHWC")
        assert not _s2d_applicable(_jnp.zeros((1, 9, 9, 8)), w8, (2, 2), 1,
                                   "NHWC")
    assert not _s2d_applicable(x3, w3, (2, 2), 1, "NCHW")      # knob off
