import os

import pytest

from poseidon_tpu.proto import (
    load_net_from_string, load_solver_from_string, parse,
)
from poseidon_tpu.proto.messages import load_net, load_solver

REF = "/root/reference"

LENET_SNIPPET = """
name: "TestNet"
layers {
  name: "conv1"
  type: CONVOLUTION
  bottom: "data"
  top: "conv1"
  blobs_lr: 1
  blobs_lr: 2
  convolution_param {
    num_output: 20
    kernel_size: 5
    stride: 1
    weight_filler { type: "xavier" }
    bias_filler { type: "constant" }
  }
}
layers {
  name: "relu1"
  type: RELU
  bottom: "conv1"
  top: "conv1"
}
"""


def test_parse_v1_layers():
    net = load_net_from_string(LENET_SNIPPET)
    assert net.name == "TestNet"
    assert len(net.layers) == 2
    c = net.layers[0]
    assert c.canonical_type() == "CONVOLUTION"
    assert c.convolution_param.num_output == 20
    assert c.convolution_param.kernel_size == 5
    assert c.convolution_param.weight_filler.type == "xavier"
    assert c.blobs_lr == [1, 2]
    assert c.param_spec(0).lr_mult == 1
    assert c.param_spec(1).lr_mult == 2
    assert net.layers[1].canonical_type() == "RELU"


def test_parse_v2_layer_format():
    net = load_net_from_string("""
    layer {
      name: "fc"
      type: "InnerProduct"
      bottom: "x" top: "y"
      param { lr_mult: 1 decay_mult: 1 }
      param { lr_mult: 2 decay_mult: 0 }
      inner_product_param { num_output: 10 }
    }
    """)
    fc = net.layers[0]
    assert fc.canonical_type() == "INNER_PRODUCT"
    assert fc.param_spec(1).lr_mult == 2
    assert fc.param_spec(1).decay_mult == 0


def test_parse_solver():
    sp = load_solver_from_string("""
    net: "train_val.prototxt"
    base_lr: 0.01
    lr_policy: "step"
    gamma: 0.1
    stepsize: 100000
    display: 20
    max_iter: 450000
    momentum: 0.9
    weight_decay: 0.0005
    solver_mode: GPU
    solver_type: NESTEROV
    test_iter: 1000
    test_interval: 1000
    random_seed: 7
    """)
    assert sp.base_lr == pytest.approx(0.01)
    assert sp.lr_policy == "step"
    assert sp.solver_type == "NESTEROV"
    assert sp.solver_mode == "GPU"
    assert sp.test_iter == [1000]
    assert sp.random_seed == 7


def test_comments_strings_escapes():
    node = parse('a: 1 # comment\nb: "hi \\"there\\"" c: -1.5e-3 d: true')
    assert node.get("a") == 1
    assert node.get("b") == 'hi "there"'
    assert node.get("c") == pytest.approx(-0.0015)
    assert node.get("d") is True


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
@pytest.mark.parametrize("relpath", [
    "examples/mnist/lenet_train_test.prototxt",
    "examples/cifar10/cifar10_quick_train_test.prototxt",
    "models/bvlc_alexnet/train_val.prototxt",
    "models/bvlc_googlenet/train_test.prototxt",
    "models/bvlc_reference_caffenet/train_val.prototxt",
])
def test_parse_reference_model_zoo(relpath):
    path = os.path.join(REF, relpath)
    if not os.path.exists(path):
        pytest.skip(f"{relpath} not in reference")
    net = load_net(path)
    assert net.layers, relpath
    for lp in net.layers:
        lp.canonical_type()  # every layer type must resolve


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
@pytest.mark.parametrize("relpath", [
    "examples/mnist/lenet_solver.prototxt",
    "examples/cifar10/cifar10_quick_solver.prototxt",
    "models/bvlc_alexnet/solver.prototxt",
    "models/bvlc_googlenet/quick_solver.prototxt",
])
def test_parse_reference_solvers(relpath):
    path = os.path.join(REF, relpath)
    if not os.path.exists(path):
        pytest.skip(f"{relpath} not in reference")
    sp = load_solver(path)
    assert sp.base_lr > 0


def test_to_prototxt_roundtrip():
    from poseidon_tpu.models import zoo
    from poseidon_tpu.proto.messages import net_to_prototxt
    from poseidon_tpu.core.net import Net
    for build_fn, shapes_fn in [(zoo.lenet, zoo.lenet_shapes),
                                (zoo.googlenet, zoo.googlenet_shapes)]:
        net_param = build_fn()
        text = net_to_prototxt(net_param)
        reparsed = load_net_from_string(text)
        assert [l.name for l in reparsed.layers] == \
            [l.name for l in net_param.layers]
        # the round-tripped net must build to identical blob shapes
        a = Net(net_param, "TRAIN", shapes_fn(2))
        b = Net(reparsed, "TRAIN", shapes_fn(2))
        assert a.blob_shapes == b.blob_shapes
        # enum identifiers must be unquoted (Caffe's parser requires it);
        # default-valued fields (e.g. pool: MAX) are correctly omitted
        assert 'type: CONVOLUTION' in text
        assert 'type: "CONVOLUTION"' not in text
    assert 'pool: AVE' in text  # googlenet's non-default pooling survives
