import os

import pytest

from poseidon_tpu.proto import (
    load_net_from_string, load_solver_from_string, parse,
)
from poseidon_tpu.proto.messages import load_net, load_solver

REF = "/root/reference"

LENET_SNIPPET = """
name: "TestNet"
layers {
  name: "conv1"
  type: CONVOLUTION
  bottom: "data"
  top: "conv1"
  blobs_lr: 1
  blobs_lr: 2
  convolution_param {
    num_output: 20
    kernel_size: 5
    stride: 1
    weight_filler { type: "xavier" }
    bias_filler { type: "constant" }
  }
}
layers {
  name: "relu1"
  type: RELU
  bottom: "conv1"
  top: "conv1"
}
"""


def test_parse_v1_layers():
    net = load_net_from_string(LENET_SNIPPET)
    assert net.name == "TestNet"
    assert len(net.layers) == 2
    c = net.layers[0]
    assert c.canonical_type() == "CONVOLUTION"
    assert c.convolution_param.num_output == 20
    assert c.convolution_param.kernel_size == 5
    assert c.convolution_param.weight_filler.type == "xavier"
    assert c.blobs_lr == [1, 2]
    assert c.param_spec(0).lr_mult == 1
    assert c.param_spec(1).lr_mult == 2
    assert net.layers[1].canonical_type() == "RELU"


def test_parse_v2_layer_format():
    net = load_net_from_string("""
    layer {
      name: "fc"
      type: "InnerProduct"
      bottom: "x" top: "y"
      param { lr_mult: 1 decay_mult: 1 }
      param { lr_mult: 2 decay_mult: 0 }
      inner_product_param { num_output: 10 }
    }
    """)
    fc = net.layers[0]
    assert fc.canonical_type() == "INNER_PRODUCT"
    assert fc.param_spec(1).lr_mult == 2
    assert fc.param_spec(1).decay_mult == 0


def test_parse_solver():
    sp = load_solver_from_string("""
    net: "train_val.prototxt"
    base_lr: 0.01
    lr_policy: "step"
    gamma: 0.1
    stepsize: 100000
    display: 20
    max_iter: 450000
    momentum: 0.9
    weight_decay: 0.0005
    solver_mode: GPU
    solver_type: NESTEROV
    test_iter: 1000
    test_interval: 1000
    random_seed: 7
    """)
    assert sp.base_lr == pytest.approx(0.01)
    assert sp.lr_policy == "step"
    assert sp.solver_type == "NESTEROV"
    assert sp.solver_mode == "GPU"
    assert sp.test_iter == [1000]
    assert sp.random_seed == 7


def test_comments_strings_escapes():
    node = parse('a: 1 # comment\nb: "hi \\"there\\"" c: -1.5e-3 d: true')
    assert node.get("a") == 1
    assert node.get("b") == 'hi "there"'
    assert node.get("c") == pytest.approx(-0.0015)
    assert node.get("d") is True


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
@pytest.mark.parametrize("relpath", [
    "examples/mnist/lenet_train_test.prototxt",
    "examples/cifar10/cifar10_quick_train_test.prototxt",
    "models/bvlc_alexnet/train_val.prototxt",
    "models/bvlc_googlenet/train_test.prototxt",
    "models/bvlc_reference_caffenet/train_val.prototxt",
])
def test_parse_reference_model_zoo(relpath):
    path = os.path.join(REF, relpath)
    if not os.path.exists(path):
        pytest.skip(f"{relpath} not in reference")
    net = load_net(path)
    assert net.layers, relpath
    for lp in net.layers:
        lp.canonical_type()  # every layer type must resolve


@pytest.mark.skipif(not os.path.isdir(REF), reason="reference not mounted")
@pytest.mark.parametrize("relpath", [
    "examples/mnist/lenet_solver.prototxt",
    "examples/cifar10/cifar10_quick_solver.prototxt",
    "models/bvlc_alexnet/solver.prototxt",
    "models/bvlc_googlenet/quick_solver.prototxt",
])
def test_parse_reference_solvers(relpath):
    path = os.path.join(REF, relpath)
    if not os.path.exists(path):
        pytest.skip(f"{relpath} not in reference")
    sp = load_solver(path)
    assert sp.base_lr > 0


def test_to_prototxt_roundtrip():
    from poseidon_tpu.models import zoo
    from poseidon_tpu.proto.messages import net_to_prototxt
    from poseidon_tpu.core.net import Net
    for build_fn, shapes_fn in [(zoo.lenet, zoo.lenet_shapes),
                                (zoo.googlenet, zoo.googlenet_shapes)]:
        net_param = build_fn()
        text = net_to_prototxt(net_param)
        reparsed = load_net_from_string(text)
        assert [l.name for l in reparsed.layers] == \
            [l.name for l in net_param.layers]
        # the round-tripped net must build to identical blob shapes
        a = Net(net_param, "TRAIN", shapes_fn(2))
        b = Net(reparsed, "TRAIN", shapes_fn(2))
        assert a.blob_shapes == b.blob_shapes
        # enum identifiers must be unquoted (Caffe's parser requires it);
        # default-valued fields (e.g. pool: MAX) are correctly omitted
        assert 'type: CONVOLUTION' in text
        assert 'type: "CONVOLUTION"' not in text
    assert 'pool: AVE' in text  # googlenet's non-default pooling survives


# --------------------------------------------------------------------------- #
# V0 legacy format upgrade (upgrade_proto.cpp:15-506)
# --------------------------------------------------------------------------- #

V0_NET = """
name: "V0Net"
layers {
  layer {
    name: "mnist" type: "data" source: "train_db" batchsize: 8
    scale: 0.00390625 cropsize: 24 mirror: true meanfile: "mean.bp"
  }
  top: "data" top: "label"
}
layers {
  layer { name: "pad1" type: "padding" pad: 2 }
  bottom: "data" top: "pad1"
}
layers {
  layer {
    name: "conv1" type: "conv" num_output: 6 kernelsize: 5 stride: 1
    group: 2 biasterm: true
    weight_filler { type: "xavier" }
    blobs_lr: 1.0 blobs_lr: 2.0 weight_decay: 1.0 weight_decay: 0.0
  }
  bottom: "pad1" top: "conv1"
}
layers { layer { name: "relu1" type: "relu" } bottom: "conv1" top: "conv1" }
layers {
  layer { name: "pool1" type: "pool" pool: MAX kernelsize: 2 stride: 2 }
  bottom: "conv1" top: "pool1"
}
layers {
  layer { name: "drop" type: "dropout" dropout_ratio: 0.3 }
  bottom: "pool1" top: "pool1"
}
layers {
  layer { name: "norm" type: "lrn" local_size: 3 alpha: 0.0001 beta: 0.5 }
  bottom: "pool1" top: "norm"
}
layers {
  layer { name: "ip1" type: "innerproduct" num_output: 10
          weight_filler { type: "gaussian" std: 0.01 } }
  bottom: "norm" top: "ip1"
}
layers {
  layer { name: "loss" type: "softmax_loss" }
  bottom: "ip1" bottom: "label" top: "loss"
}
"""


def test_v0_net_upgrades():
    net = load_net_from_string(V0_NET)
    types = [l.type for l in net.layers]
    # padding layer is deleted, its pad folded into conv1
    assert "padding" not in " ".join(types)
    assert types == ["DATA", "CONVOLUTION", "RELU", "POOLING", "DROPOUT",
                     "LRN", "INNER_PRODUCT", "SOFTMAX_LOSS"]
    conv = net.layers[1]
    assert conv.name == "conv1"
    assert conv.bottom == ["data"]          # rewired past the padding layer
    assert conv.convolution_param.pad == 2  # folded from the padding layer
    assert conv.convolution_param.num_output == 6
    assert conv.convolution_param.kernel_size == 5
    assert conv.convolution_param.group == 2
    assert conv.convolution_param.weight_filler.type == "xavier"
    assert conv.blobs_lr == [1.0, 2.0]
    assert conv.weight_decay == [1.0, 0.0]
    data = net.layers[0]
    assert data.data_param.source == "train_db"
    assert data.data_param.batch_size == 8
    # V0 scale/cropsize/mirror/meanfile land in transform_param
    assert data.transform_param.scale == pytest.approx(0.00390625)
    assert data.transform_param.crop_size == 24
    assert data.transform_param.mirror is True
    assert data.transform_param.mean_file == "mean.bp"
    pool = net.layers[3]
    assert pool.pooling_param.pool == "MAX"
    assert pool.pooling_param.kernel_size == 2
    assert net.layers[4].dropout_param.dropout_ratio == pytest.approx(0.3)
    assert net.layers[5].lrn_param.local_size == 3
    assert net.layers[6].inner_product_param.num_output == 10
    # the upgraded net must actually build and run shape inference
    from poseidon_tpu.core.net import Net
    built = Net(net, "TRAIN", source_shapes={"data": (8, 2, 24, 24),
                                             "label": (8,)})
    assert built.blob_shapes["conv1"] == (8, 6, 24, 24)


def test_v0_unknown_field_raises():
    from poseidon_tpu.proto.prototxt import PrototxtError
    bad = """
    layers { layer { name: "x" type: "conv" num_output: 2 bogus_field: 1 }
             bottom: "data" top: "x" }
    """
    with pytest.raises(PrototxtError, match="bogus_field"):
        load_net_from_string(bad)


def test_v1_data_transform_migration():
    net = load_net_from_string("""
    layers {
      name: "d" type: DATA top: "data" top: "label"
      data_param { source: "db" batch_size: 4 scale: 0.5 crop_size: 12
                   mirror: true }
    }
    layers { name: "s" type: SILENCE bottom: "data" }
    layers { name: "s2" type: SILENCE bottom: "label" }
    """)
    t = net.layers[0].transform_param
    assert t.scale == 0.5 and t.crop_size == 12 and t.mirror is True
