"""Mosaic-lowering regression gate: compile Pallas kernels with the REAL
TPU compiler, no hardware needed.

Round-4 verdict weak #8: everything green ran on the CPU interpret path, so
a Mosaic lowering regression (the round-3 on-chip failure mode) was
invisible to the suite. The local libtpu can AOT-compile for an abstract
v5e topology (jax.experimental.topologies); these tests push the flash
attention forward+backward through that pipeline — the same Mosaic passes
the chip runs — on every suite run. Numerics on real silicon remain
hardware evidence (scripts/tpu_evidence.py pallas_mosaic section); the
lowering half is now a plain test.

Skips (not fails) when another process holds the libtpu lockfile or the
plugin cannot initialize — those are environment states, not regressions.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CODE = r"""
import os, sys
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("TPU_ACCELERATOR_TYPE", "v5e-8")
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp, numpy as np
from jax.experimental import topologies
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
try:
    topo = topologies.get_topology_desc("v5e:2x4", platform="tpu")
except Exception as e:
    print("SKIP:", e)
    sys.exit(3)
from poseidon_tpu.ops.pallas_kernels import flash_attention, lrn_fused
m1 = Mesh(np.array(topo.devices[:1]), ("x",))
sh = NamedSharding(m1, P())
q = jax.ShapeDtypeStruct((2, 4, 1024, 64), jnp.bfloat16, sharding=sh)

def fwd(q, k, v):
    return flash_attention(q, k, v, causal=True, interpret=False)

def bwd(q, k, v):
    f = lambda a, b, c: flash_attention(a, b, c, causal=True,
                                        interpret=False).sum()
    return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

for name, fn, avals in [("fwd", fwd, (q, q, q)), ("bwd", bwd, (q, q, q))]:
    txt = jax.jit(fn).lower(*avals).compile().as_text()
    assert txt.count("tpu_custom_call") >= 1, name
    print("OK", name)
x = jax.ShapeDtypeStruct((4, 96, 27, 27), jnp.float32, sharding=sh)
txt = jax.jit(lambda x: lrn_fused(x, 5, 1e-4, 0.75, 1.0,
                                  interpret=False)).lower(x) \
    .compile().as_text()
assert txt.count("tpu_custom_call") >= 1, "lrn"
print("OK lrn")
# grad routes through the one-pass Pallas BACKWARD kernel on TPU — it must
# pass Mosaic too (fwd-only coverage shipped an unlowered bwd in round 5).
# jax.grad discards the primal output, so XLA DCEs the FORWARD custom call
# (its residual is just x): the one surviving call IS the backward kernel.
txt = jax.jit(jax.grad(lambda x: lrn_fused(
    x, 5, 1e-4, 0.75, 1.0, interpret=False).sum())).lower(x) \
    .compile().as_text()
assert txt.count("tpu_custom_call") >= 1, "lrn bwd"
print("OK lrn_bwd")
"""


@pytest.mark.slow
def test_flash_kernels_mosaic_compile_for_v5e():
    """flash fwd/bwd + fused LRN must pass the real Mosaic pipeline."""
    r = subprocess.run(
        [sys.executable, "-c", _CODE.format(repo=REPO)],
        capture_output=True, text=True, timeout=900,
        env={k: v for k, v in os.environ.items()
             if k != "PALLAS_AXON_POOL_IPS"})
    if r.returncode == 3 or "lockfile" in (r.stdout + r.stderr):
        pytest.skip(f"libtpu AOT unavailable: "
                    f"{(r.stdout + r.stderr).strip()[-200:]}")
    assert r.returncode == 0, r.stdout[-1500:] + r.stderr[-1500:]
    assert "OK fwd" in r.stdout and "OK bwd" in r.stdout \
        and "OK lrn" in r.stdout and "OK lrn_bwd" in r.stdout
