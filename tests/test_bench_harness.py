"""bench.py contract tests: ONE JSON line on every path, no silent CPU.

The driver runs bench.py at round end and records its single JSON line;
these tests pin the three behaviors the hardened harness promises
(round-1 verdict item 1): a probe that cannot hang the bench, a refusal to
report CPU as a TPU number, and the explicit CPU smoke mode that still
emits the full line shape."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(env_extra, timeout=420):
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    env.update(env_extra)
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=timeout,
                       env=env, cwd=REPO)
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1, f"expected ONE JSON line, got: {r.stdout!r}"
    return r.returncode, json.loads(lines[0])


def test_refuses_silent_cpu_fallback():
    """Default mode on a CPU-only machine must FAIL with the structured
    line (never report CPU throughput as the TPU headline), and any
    carried-forward last_good must be LOUDLY labeled stale (round-4
    verdict: a last_good passing silently as fresh)."""
    rc, payload = _run_bench({"POSEIDON_BENCH_PROBE_TIMEOUT": "60",
                              "POSEIDON_BENCH_PROBE_ATTEMPTS": "1"})
    assert rc != 0
    assert payload["value"] == 0.0
    assert "refusing" in payload["error"] or "unavailable" in payload["error"]
    assert payload["metric"] == \
        "alexnet_ilsvrc12_train_images_per_sec_per_chip"
    if os.path.exists(os.path.join(REPO, "BENCH_last_good.json")):
        assert payload["last_good"]["stale_carryover"] is True
        assert "age_hours" in payload["last_good"]


def test_probe_backend_reports_platform():
    sys.path.insert(0, REPO)
    import bench
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    info = bench.probe_backend(timeout_s=120, attempts=1)
    assert info.get("platform") == "cpu"


@pytest.mark.slow
def test_cpu_smoke_emits_full_line():
    """POSEIDON_BENCH_CPU=1 with tiny knobs: rc 0, labeled cpu, value > 0,
    and the cost-analysis extras present (the ADVICE fix)."""
    rc, payload = _run_bench({
        "POSEIDON_BENCH_CPU": "1", "POSEIDON_BENCH_BATCH": "1",
        "POSEIDON_BENCH_IMAGE": "67", "POSEIDON_BENCH_CLASSES": "8",
        "POSEIDON_BENCH_ITERS": "1", "POSEIDON_BENCH_AB": "0",
        "POSEIDON_BENCH_LAYOUT_AB": "0", "POSEIDON_BENCH_TOPK": "0",
        "POSEIDON_BENCH_GOOGLENET": "0", "POSEIDON_BENCH_LM": "0"})
    assert rc == 0
    assert payload["backend"] == "cpu"
    assert payload["value"] > 0
    assert payload["alexnet_step_flops_per_device"] > 0
    # per-section checkpointing: the completed headline section must have
    # landed on disk even before the final line (a mid-run SIGKILL loses
    # nothing — round-3's 1200 s rc -9 whole-window loss, made impossible)
    with open(os.path.join(REPO, "evidence", "bench_partial.json")) as f:
        partial = json.load(f)
    assert "alexnet" in partial["sections_done"]
    assert partial["alexnet_step_ms"] > 0


def _run_bench_serving(env_extra, timeout=420):
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env.update({"JAX_PLATFORMS": "cpu",
                "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    env.update(env_extra)
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                        "serving"],
                       capture_output=True, text=True, timeout=timeout,
                       env=env, cwd=REPO)
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) == 1, f"expected ONE JSON line, got: {r.stdout!r}"
    return r.returncode, json.loads(lines[0])


def test_serving_mode_refuses_silent_cpu():
    """`bench.py serving` keeps the no-silent-CPU contract: without the
    explicit smoke flag on a CPU-only machine it fails with the structured
    serving line."""
    rc, payload = _run_bench_serving({"POSEIDON_BENCH_PROBE_TIMEOUT": "60",
                                      "POSEIDON_BENCH_PROBE_ATTEMPTS": "1"})
    assert rc != 0
    assert payload["metric"] == "serving_p99_ms"
    assert payload["value"] == 0.0
    assert "refusing" in payload["error"] or "unavailable" in payload["error"]


@pytest.mark.slow
def test_serving_mode_cpu_smoke_emits_full_line():
    """Explicit CPU smoke: rc 0, the BENCH line shape, and the serving
    extras (p50/p99/throughput/batch_fill) all present."""
    rc, payload = _run_bench_serving({
        "POSEIDON_BENCH_CPU": "1",
        "POSEIDON_BENCH_SERVE_REQUESTS": "40",
        "POSEIDON_BENCH_SERVE_CONCURRENCY": "2",
        "POSEIDON_BENCH_SERVE_BUCKETS": "1,2,4"})
    assert rc == 0
    assert payload["metric"] == "serving_p99_ms"
    assert payload["unit"] == "ms"
    assert payload["value"] > 0 and payload["vs_baseline"] > 0
    assert payload["p50_ms"] is not None
    assert payload["throughput_rps"] > 0
    assert payload["cpu_smoke"] is True and payload["platform"] == "cpu"
