"""Serving-fleet chaos suite (ISSUE 13): least-loaded routing, replica
health states and failover, rolling hot-reload, per-replica stats, the
open-loop load generator, and device pinning.

Everything is CPU-safe, port 0 on loopback only, daemon threads only.
Deterministic where it matters: routing units drive duck-typed fake
executors (no timing races); the kill-1-of-3 acceptance scenario runs
real sockets through runtime/faults.py's proxy and asserts the invariant
(zero accepted requests lost — only explicit sheds), not a schedule.
"""

import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.serving

DEPLOY_NET = """
name: "fleetnet"
input: "data"
input_dim: 1 input_dim: 3 input_dim: 8 input_dim: 8
layers { name: "conv" type: CONVOLUTION bottom: "data" top: "conv"
  convolution_param { num_output: 4 kernel_size: 3
    weight_filler { type: "xavier" } } }
layers { name: "fc" type: INNER_PRODUCT bottom: "conv" top: "fc"
  inner_product_param { num_output: 3 weight_filler { type: "xavier" } } }
layers { name: "prob" type: SOFTMAX bottom: "fc" top: "prob" }
"""


def _rows(n, seed=0):
    return np.random.RandomState(seed).randn(n, 3, 8, 8).astype(np.float32)


def _build_executor(buckets=(1, 2, 4), device=None, seed=7):
    import jax
    from poseidon_tpu.core.net import Net
    from poseidon_tpu.proto.messages import load_net_from_string
    from poseidon_tpu.serving.executor import BucketedExecutor

    net = Net(load_net_from_string(DEPLOY_NET), "TEST")
    params = net.init(jax.random.PRNGKey(seed))
    return BucketedExecutor(net, params, buckets=buckets, device=device)


class FakeExecutor:
    """Duck-typed replica engine: optional per-call stall, a poison switch
    (``die.set()`` -> every dispatch raises, the replica-death lever), and
    a per-instance dispatch log."""

    def __init__(self, max_batch=4, delay_s=0.0):
        self.input_names = ["x"]
        self.max_batch = max_batch
        self.delay_s = delay_s
        self.die = threading.Event()
        self.gate = None          # optional Event the dispatch blocks on
        self.rows_served = 0
        self.params_version = 0
        self.infers = 0

    def infer(self, inputs):
        if self.gate is not None:
            self.gate.wait(10.0)
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.die.is_set():
            raise RuntimeError("device lost")
        rows = int(np.shape(inputs["x"])[0])
        self.rows_served += rows
        self.infers += 1
        return {"y": np.asarray(inputs["x"], np.float32) * 2.0}

    def swap_params(self, new_params):
        self.params_version += 1
        return self.params_version


def _fake_fleet(n=3, delay_s=0.0, **kw):
    from poseidon_tpu.serving.fleet import ReplicaManager

    exs = [FakeExecutor(delay_s=delay_s) for _ in range(n)]
    return ReplicaManager(exs, **kw), exs


# --------------------------------------------------------------------------- #
# routing
# --------------------------------------------------------------------------- #

def test_least_loaded_routing_skews_to_idle_replica():
    """With replica 0's flush thread held busy (queued work = nonzero
    load), every subsequent request lands on an idle replica — the
    routing signal actually routes."""
    mgr, exs = _fake_fleet(3)
    try:
        exs[0].gate = threading.Event()      # replica 0 blocks in dispatch
        blocker = threading.Thread(
            target=lambda: mgr.replicas[0].batcher.submit(
                {"x": np.ones((1, 2), np.float32)}),
            daemon=True)
        blocker.start()
        deadline = time.monotonic() + 5.0
        while mgr.replicas[0].load() == 0.0:
            assert time.monotonic() < deadline, "blocker never dispatched"
            time.sleep(0.002)
        for i in range(20):
            out, rep = mgr.submit({"x": np.full((1, 2), i, np.float32)})
            assert rep.index != 0, "router sent work to the busy replica"
            np.testing.assert_array_equal(out["y"], np.full((1, 2), 2.0 * i))
        assert exs[1].infers + exs[2].infers == 20
        with mgr.replicas[0]._lock:
            assert mgr.replicas[0].routed == 0
        exs[0].gate.set()
        blocker.join(timeout=10.0)
    finally:
        for ex in exs:
            if ex.gate is not None:
                ex.gate.set()
        mgr.shutdown()


def test_routing_excludes_warming_draining_and_dead():
    from poseidon_tpu.serving.batcher import ShedError
    from poseidon_tpu.serving.fleet import DEAD, DRAINING, SERVING, WARMING

    mgr, exs = _fake_fleet(3)
    try:
        mgr._transition(mgr.replicas[0], DRAINING, reason="test")
        mgr._transition(mgr.replicas[2], DRAINING, reason="test")
        for _ in range(5):
            _, rep = mgr.submit({"x": np.ones((1, 2), np.float32)})
            assert rep.index == 1
        # no serving replica at all -> immediate explicit shed
        mgr._transition(mgr.replicas[1], DRAINING, reason="test")
        t0 = time.monotonic()
        with pytest.raises(ShedError, match="no serving replica"):
            mgr.submit({"x": np.ones((1, 2), np.float32)})
        assert time.monotonic() - t0 < 0.5, "fleet shed must be immediate"
        assert mgr.fleet_sheds == 1
    finally:
        mgr.shutdown()


def test_full_fleet_queues_shed_explicitly():
    """Every serving replica at queue capacity -> ShedError naming the
    backpressure, not a hang and not a reroute loop."""
    from poseidon_tpu.serving.batcher import ShedError
    from poseidon_tpu.serving.fleet import ReplicaManager

    exs = [FakeExecutor() for _ in range(2)]
    for ex in exs:
        ex.gate = threading.Event()          # hold both flush threads
    mgr = ReplicaManager(exs, max_queue=1)
    threads = []
    try:
        # one in-flight + one queued per replica = both queues full
        for rep in mgr.replicas:
            for _ in range(2):
                t = threading.Thread(
                    target=lambda rep=rep: rep.batcher.submit(
                        {"x": np.ones((1, 2), np.float32)}),
                    daemon=True)
                t.start()
                threads.append(t)
                time.sleep(0.05)
        with pytest.raises(ShedError, match="queue capacity"):
            mgr.submit({"x": np.ones((1, 2), np.float32)})
    finally:
        for ex in exs:
            ex.gate.set()
        for t in threads:
            t.join(timeout=10.0)
        mgr.shutdown()


# --------------------------------------------------------------------------- #
# failure detection + failover
# --------------------------------------------------------------------------- #

def test_replica_death_fails_over_without_losing_requests():
    """Manager-level determinism: kill one replica's executor while its
    queue holds work; every request still completes OK on a survivor
    (fan-out error -> reroute), the replica is DEAD, and nothing sheds."""
    from poseidon_tpu.serving.fleet import DEAD, SERVING

    mgr, exs = _fake_fleet(2)
    try:
        exs[0].gate = threading.Event()
        exs[0].die.set()                     # dies on its NEXT dispatch
        results = []
        errors = []

        def one(i):
            try:
                results.append(mgr.submit(
                    {"x": np.full((1, 2), i, np.float32)}))
            except BaseException as e:  # noqa: BLE001 — the assertion
                errors.append(e)

        # first request routes to replica 0 (tie-break by index) and will
        # find the poisoned executor once the gate opens
        threads = [threading.Thread(target=one, args=(i,), daemon=True)
                   for i in range(6)]
        for t in threads:
            t.start()
            time.sleep(0.02)
        exs[0].gate.set()
        for t in threads:
            t.join(timeout=10.0)
        assert not errors, f"request lost to a replica death: {errors[0]}"
        assert len(results) == 6
        states = mgr.state_counts()
        assert states[DEAD] == 1 and states[SERVING] == 1
        assert mgr.failovers >= 1
        assert mgr.replicas[0].death_reason and \
            "device lost" in mgr.replicas[0].death_reason
        # the dead replica never comes back into the routing set
        for i in range(4):
            _, rep = mgr.submit({"x": np.ones((1, 2), np.float32)})
            assert rep.index == 1
    finally:
        for ex in exs:
            if ex.gate is not None:
                ex.gate.set()
        mgr.shutdown()


def test_kill_one_of_three_chaos_under_load():
    """The acceptance scenario, through the real front door AND the fault
    proxy: 3 replicas under sustained socket load, one replica dies
    mid-run, then a full network partition (sever_all) on top. Zero
    accepted requests are lost — every request either completes OK or is
    an explicit shed — and p99 stays bounded through the failover."""
    from poseidon_tpu.runtime.faults import FaultProxy
    from poseidon_tpu.serving.client import run_load
    from poseidon_tpu.serving.fleet import DEAD
    from poseidon_tpu.serving.server import InferenceServer

    mgr, exs = _fake_fleet(3, delay_s=0.002)
    srv = InferenceServer(fleet=mgr)
    proxy = FaultProxy(srv.addr)
    try:
        box = {}

        def load():
            box["result"] = run_load(
                proxy.addr, lambda i: {"x": np.ones((2, 3), np.float32)},
                n_requests=150, concurrency=6, retry_deadline_s=10.0)

        t = threading.Thread(target=load, daemon=True)
        t.start()
        time.sleep(0.15)
        exs[0].die.set()                     # replica death mid-request
        time.sleep(0.15)
        proxy.sever_all()                    # partition every connection
        t.join(timeout=60.0)
        assert not t.is_alive(), "load generator wedged"
        r = box["result"]
        # the invariant: only explicit sheds are lost, nothing errors
        assert r["error"] == 0 and r["deadline"] == 0, r
        assert r["ok"] + r["shed"] == 150, r
        assert r["ok"] > 0
        assert r["p99_ms"] is not None and r["p99_ms"] < 5000.0
        assert mgr.state_counts()[DEAD] == 1
        assert mgr.deaths == 1 and mgr.failovers >= 1
        # survivors carried the load
        assert exs[1].infers + exs[2].infers > 0
    finally:
        proxy.close()
        srv.shutdown()


def test_failover_deadline_is_absolute_across_reroutes():
    """A request's deadline never restarts on failover: with the only
    survivor unable to answer inside the remaining budget, the reroute
    surfaces DeadlineError instead of silently extending the contract."""
    from poseidon_tpu.serving.batcher import DeadlineError

    mgr, exs = _fake_fleet(2)
    try:
        exs[0].gate = threading.Event()
        exs[0].die.set()
        exs[1].gate = threading.Event()      # survivor can't answer either
        t0 = time.monotonic()
        with pytest.raises(DeadlineError):
            # replica 0 holds the request past the deadline, then dies;
            # the reroute must see an exhausted budget, not a fresh one
            threading.Timer(0.25, exs[0].gate.set).start()
            mgr.submit({"x": np.ones((1, 2), np.float32)}, deadline_s=0.1)
        assert time.monotonic() - t0 < 5.0
    finally:
        for ex in exs:
            if ex.gate is not None:
                ex.gate.set()
        mgr.shutdown()


# --------------------------------------------------------------------------- #
# rolling hot-reload
# --------------------------------------------------------------------------- #

def test_rolling_reload_invariant_under_load():
    """A full fleet reload under live socket load: at most ONE replica
    draining at any instant, zero request failures, every replica on the
    new params and generation afterwards — and results actually flip."""
    import jax

    from poseidon_tpu.serving.client import ServingClient
    from poseidon_tpu.serving.fleet import DRAINING, ReplicaManager
    from poseidon_tpu.serving.server import InferenceServer

    exs = [_build_executor() for _ in range(3)]
    transitions = []
    tr_lock = threading.Lock()

    def observer(index, old, new, reason):
        with tr_lock:
            transitions.append((index, old, new))

    mgr = ReplicaManager(exs, on_transition=observer)
    srv = InferenceServer(fleet=mgr)
    x = _rows(2)
    errors = []
    stop = threading.Event()

    def hammer():
        from poseidon_tpu.serving.client import ServingClient as C
        c = C(srv.addr)
        try:
            while not stop.is_set():
                try:
                    c.infer({"data": x})
                except Exception as e:  # noqa: BLE001 — the assertion
                    errors.append(e)
                    return
        finally:
            c.close()

    cli = ServingClient(srv.addr)
    try:
        before = cli.infer({"data": x})["prob"]
        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        doubled = jax.tree_util.tree_map(lambda v: v * 2.0, exs[0]._params)
        swapped = mgr.rolling_reload(doubled)
        after = cli.infer({"data": x})["prob"]
        time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        assert not errors, \
            f"request failed during rolling reload: {errors[0]}"
        assert swapped == 3
        assert mgr.max_concurrent_draining == 1, \
            "more than one replica was draining at once"
        # the transition log agrees: DRAINING entries never overlap
        draining = 0
        for _, old, new in transitions:
            if new == DRAINING:
                draining += 1
                assert draining <= 1
            elif old == DRAINING:
                draining -= 1
        assert not np.allclose(before, after)
        for rep in mgr.replicas:
            assert rep.reload_generation == 1
            assert rep.executor.params_version == 1
    finally:
        stop.set()
        cli.close()
        srv.shutdown()


def _snapshot_params(prefix, net, params, it):
    import jax.numpy as jnp
    from poseidon_tpu.parallel.trainer import init_train_state
    from poseidon_tpu.runtime.checkpoint import snapshot

    state = init_train_state(params)
    state = state._replace(solver=state.solver._replace(
        it=jnp.asarray(it, jnp.int32)))
    return snapshot(prefix, net, params, state)


def test_fleet_reloader_rolls_snapshot_through_every_replica(tmp_path):
    """FleetReloader = the single-executor reloader's discovery rules +
    ONE load + rolling_reload: all replicas land on the new snapshot, a
    stale snapshot is a no-op, and the server `reload` op drives it."""
    import jax

    from poseidon_tpu.serving.client import ServingClient
    from poseidon_tpu.serving.fleet import ReplicaManager
    from poseidon_tpu.serving.reloader import FleetReloader
    from poseidon_tpu.serving.server import InferenceServer

    exs = [_build_executor(buckets=(1, 2)) for _ in range(3)]
    mgr = ReplicaManager(exs)
    prefix = str(tmp_path / "snap" / "fleetnet")
    _, seed_path = _snapshot_params(prefix, exs[0].net, exs[0]._params, it=1)
    rel = FleetReloader(mgr, prefix, start=False, current_path=seed_path)
    assert rel.check_now() is False          # nothing newer than the seed
    srv = InferenceServer(fleet=mgr, reloader=rel)
    cli = ServingClient(srv.addr)
    try:
        doubled = jax.tree_util.tree_map(lambda v: v * 2.0, exs[0]._params)
        _snapshot_params(prefix, exs[0].net, doubled, it=5)
        reply = cli.reload()
        assert reply["ok"] and reply["reloaded"] is True
        assert reply["reload_generation"] == 1
        assert rel.reloads == 1
        for rep in mgr.replicas:
            assert rep.executor.params_version == 1
            assert rep.reload_generation == 1
        # an OLDER snapshot later must not roll the fleet backwards
        _snapshot_params(prefix, exs[0].net, exs[0]._params, it=3)
        assert rel.check_now() is False and rel.reloads == 1
    finally:
        cli.close()
        srv.shutdown()


def test_partial_reload_raises_typed_error_with_swapped_count():
    """A replica that cannot drain inside the timeout keeps its old params
    and the pass surfaces PartialReloadError (typed: the fleet reloader
    advances past it instead of re-draining healthy replicas every poll);
    the healthy replica still swapped."""
    from poseidon_tpu.serving.fleet import PartialReloadError, SERVING

    mgr, exs = _fake_fleet(2)
    try:
        exs[0].gate = threading.Event()      # replica 0 can never drain
        blocker = threading.Thread(
            target=lambda: mgr.replicas[0].batcher.submit(
                {"x": np.ones((1, 2), np.float32)}),
            daemon=True)
        blocker.start()
        deadline = time.monotonic() + 5.0
        while mgr.replicas[0].load() == 0.0:
            assert time.monotonic() < deadline
            time.sleep(0.002)
        with pytest.raises(PartialReloadError) as ei:
            mgr.rolling_reload({"w": np.zeros(1, np.float32)},
                               drain_timeout_s=0.1)
        assert ei.value.swapped == 1 and len(ei.value.errors) == 1
        assert exs[0].params_version == 0    # wedged: old params kept
        assert exs[1].params_version == 1
        with mgr.replicas[0]._lock:
            assert mgr.replicas[0].state == SERVING   # back in the set
        exs[0].gate.set()
        blocker.join(timeout=10.0)
    finally:
        for ex in exs:
            if ex.gate is not None:
                ex.gate.set()
        mgr.shutdown()


def test_rolling_reload_skips_dead_replicas():
    from poseidon_tpu.serving.fleet import DEAD

    mgr, exs = _fake_fleet(3)
    try:
        mgr._mark_dead(mgr.replicas[1], "test kill")
        swapped = mgr.rolling_reload({"w": np.zeros(1, np.float32)})
        assert swapped == 2
        assert exs[0].params_version == 1 and exs[2].params_version == 1
        assert exs[1].params_version == 0    # dead replicas never reload
        with mgr.replicas[1]._lock:
            assert mgr.replicas[1].state == DEAD
    finally:
        mgr.shutdown()


# --------------------------------------------------------------------------- #
# warming
# --------------------------------------------------------------------------- #

def test_build_warms_replicas_through_warming_state():
    """ReplicaManager.build with a gated factory: the fleet sheds while
    every replica is WARMING and serves the moment one lands."""
    from poseidon_tpu.serving.batcher import ShedError
    from poseidon_tpu.serving.fleet import ReplicaManager, SERVING, WARMING

    release = threading.Event()

    def factory(device):
        release.wait(10.0)
        return FakeExecutor()

    mgr = ReplicaManager.build(factory, 2, warm_async=True)
    try:
        assert mgr.state_counts()[WARMING] == 2
        with pytest.raises(ShedError, match="no serving replica"):
            mgr.submit({"x": np.ones((1, 2), np.float32)})
        release.set()
        deadline = time.monotonic() + 10.0
        while mgr.state_counts()[SERVING] < 2:
            assert time.monotonic() < deadline, "replicas never warmed"
            time.sleep(0.01)
        out, _ = mgr.submit({"x": np.ones((1, 2), np.float32)})
        assert out["y"].shape == (1, 2)
    finally:
        release.set()
        mgr.shutdown()


def test_late_warming_replica_catches_up_to_rolled_params():
    """warm_async + a reload landing while a replica is still compiling:
    the late replica must come up on the ROLLED params (same generation),
    never its stale factory weights."""
    from poseidon_tpu.serving.fleet import ReplicaManager, SERVING

    release = threading.Event()
    slow_ex = FakeExecutor()

    def factory(device):
        if factory.first:
            factory.first = False
            return FakeExecutor()
        release.wait(10.0)                   # replica 1 warms slowly
        return slow_ex

    factory.first = True
    mgr = ReplicaManager.build(factory, 2, warm_async=True)
    try:
        deadline = time.monotonic() + 10.0
        while mgr.state_counts()[SERVING] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        mgr.rolling_reload({"w": np.ones(1, np.float32)})
        assert mgr.replicas[0].reload_generation == 1
        release.set()                        # replica 1 warms AFTER the roll
        deadline = time.monotonic() + 10.0
        while slow_ex.params_version < 1:
            assert time.monotonic() < deadline, \
                "late replica never caught up to the rolled params"
            time.sleep(0.01)
        deadline = time.monotonic() + 10.0
        while mgr.replicas[1].reload_generation < 1:
            assert time.monotonic() < deadline
            time.sleep(0.01)
    finally:
        release.set()
        mgr.shutdown()


def test_failed_warmup_is_a_dead_replica_not_a_dead_fleet():
    from poseidon_tpu.serving.fleet import DEAD, ReplicaManager, SERVING

    calls = {"n": 0}

    def factory(device):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("OOM during bucket warm-up")
        return FakeExecutor()

    mgr = ReplicaManager.build(factory, 2)
    try:
        states = mgr.state_counts()
        assert states[DEAD] == 1 and states[SERVING] == 1
        out, rep = mgr.submit({"x": np.ones((1, 2), np.float32)})
        assert out["y"].shape == (1, 2) and rep.index == 1
    finally:
        mgr.shutdown()


# --------------------------------------------------------------------------- #
# stats surface
# --------------------------------------------------------------------------- #

def test_fleet_stats_has_per_replica_rows_and_aggregates():
    from poseidon_tpu.serving.client import ServingClient
    from poseidon_tpu.serving.server import InferenceServer

    mgr, exs = _fake_fleet(3)
    srv = InferenceServer(fleet=mgr)
    cli = ServingClient(srv.addr)
    try:
        for i in range(6):
            cli.infer({"x": np.ones((1, 2), np.float32)})
        st = cli.stats()
        assert st["n_replicas"] == 3
        assert set(st["replicas"]) == {"0", "1", "2"}
        for row in st["replicas"].values():
            for key in ("state", "queue_depth", "batch_fill", "shed",
                        "reload_generation", "load", "routed", "failures",
                        "latency"):
                assert key in row, f"replica row missing {key}"
        assert sum(r["routed"] for r in st["replicas"].values()) == 6
        for key in ("states", "routing", "latency", "replica_latency",
                    "reload_generation", "max_concurrent_draining",
                    "deaths", "bad_frames", "connections", "uptime_s"):
            assert key in st, f"fleet stats missing {key}"
        assert st["routing"]["routed"] == 6
        h = cli.health()
        assert h["ok"] and h["states"]["SERVING"] == 3
    finally:
        cli.close()
        srv.shutdown()


def test_fleet_stats_flatten_on_metrics_endpoint():
    """The per-replica rows render as replicas.<i>.<key>=... on the live
    metrics endpoint — the fleet health surface is one curl away."""
    import urllib.request

    from poseidon_tpu.runtime.metrics import MetricsServer
    from poseidon_tpu.serving.server import InferenceServer

    mgr, _ = _fake_fleet(2)
    srv = InferenceServer(fleet=mgr)
    msrv = MetricsServer(srv.stats, port=0)
    try:
        mgr.submit({"x": np.ones((1, 2), np.float32)})
        srv.stats_snapshot()                 # refresh the registry section
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{msrv.port}/", timeout=5.0).read().decode()
        assert "serving.replicas.0.queue_depth=" in body
        assert "serving.routing.routed=1" in body
    finally:
        msrv.close()
        srv.shutdown()


def test_server_requires_exactly_one_backend():
    from poseidon_tpu.serving.server import InferenceServer

    with pytest.raises(ValueError, match="exactly one"):
        InferenceServer()
    mgr, _ = _fake_fleet(1)
    try:
        with pytest.raises(ValueError, match="exactly one"):
            InferenceServer(executor=FakeExecutor(), fleet=mgr)
    finally:
        mgr.shutdown()


def test_merged_latency_summary_pools_windows():
    from poseidon_tpu.runtime.metrics import LatencyWindow

    a, b = LatencyWindow(), LatencyWindow()
    for v in (0.010, 0.020, 0.030):
        a.record(v)
    b.record(0.100)
    merged = LatencyWindow.merged_summary([a, b])
    assert merged["count"] == 4
    assert merged["p50_ms"] == pytest.approx(20.0, abs=10.001)
    assert merged["p99_ms"] == pytest.approx(100.0)
    assert LatencyWindow.merged_summary([]) == {"count": 0}


# --------------------------------------------------------------------------- #
# open-loop load generator
# --------------------------------------------------------------------------- #

def test_open_loop_load_generator_paces_offered_rate():
    """offered_rps fixes ARRIVALS: 30 requests at 100 req/s take ~0.3 s of
    wall clock even though the (fast) server could absorb them instantly —
    the opposite of closed-loop self-throttling — and the result carries
    the goodput/late-fire fields the fleet curves are built from."""
    from poseidon_tpu.serving.client import run_load
    from poseidon_tpu.serving.server import InferenceServer

    srv = InferenceServer(executor=FakeExecutor(), max_delay_s=0.0)
    try:
        r = run_load(srv.addr, lambda i: {"x": np.ones((1, 2), np.float32)},
                     n_requests=30, concurrency=8, offered_rps=100.0)
        assert r["ok"] == 30 and r["error"] == 0
        assert r["offered_rps"] == 100.0
        assert r["wall_s"] >= 0.25, \
            "open loop did not pace arrivals (closed-loop blast?)"
        assert "late_fires" in r and "achieved_rps" in r
        assert r["goodput_rps"] <= 130.0
        # closed loop on the same server: no pacing fields
        r2 = run_load(srv.addr,
                      lambda i: {"x": np.ones((1, 2), np.float32)},
                      n_requests=20, concurrency=4)
        assert "offered_rps" not in r2 and r2["goodput_rps"] > 0
        # a zero rate is refused loudly, never a silent worker death
        with pytest.raises(ValueError, match="offered_rps"):
            run_load(srv.addr,
                     lambda i: {"x": np.ones((1, 2), np.float32)},
                     n_requests=5, offered_rps=0.0)
    finally:
        srv.shutdown()


# --------------------------------------------------------------------------- #
# device pinning + CLI fleet builder
# --------------------------------------------------------------------------- #

def test_executor_device_pinning_places_params_and_matches():
    import jax

    devs = jax.devices()
    assert len(devs) >= 2, "conftest should provide the 8-device CPU mesh"
    pinned = _build_executor(device=devs[1])
    free = _build_executor()
    leaf = jax.tree_util.tree_leaves(pinned._params)[0]
    assert leaf.devices() == {devs[1]}
    x = _rows(2)
    np.testing.assert_array_equal(pinned.infer({"data": x})["prob"],
                                  free.infer({"data": x})["prob"])
    # a swap lands the new tree on the pinned device too
    import jax.numpy as jnp
    pinned.swap_params(jax.tree_util.tree_map(lambda v: v * 2.0,
                                              free._params))
    leaf = jax.tree_util.tree_leaves(pinned._params)[0]
    assert leaf.devices() == {devs[1]}


def test_build_serving_fleet_pins_round_robin_and_validates(tmp_path):
    import jax

    from poseidon_tpu.runtime.cli import (_resolve_fleet_devices,
                                          build_serving_fleet)

    devs = jax.devices()
    picked = _resolve_fleet_devices("0,2", 2)
    assert picked == [devs[0], devs[2]]
    with pytest.raises(SystemExit, match="no such device index"):
        _resolve_fleet_devices("99", 2)
    with pytest.raises(SystemExit, match="comma-separated"):
        _resolve_fleet_devices("a,b", 2)
    assert _resolve_fleet_devices("", 1) == []

    model = tmp_path / "deploy.prototxt"
    model.write_text(DEPLOY_NET)
    mgr = build_serving_fleet(str(model), "", "1,2", 3,
                              devices_spec="0,1")
    try:
        assert len(mgr.replicas) == 3
        labels = [rep.device_label for rep in mgr.replicas]
        assert labels[0] == labels[2] == str(devs[0])   # round-robin
        assert labels[1] == str(devs[1])
        for rep in mgr.replicas:
            leaf = jax.tree_util.tree_leaves(rep.executor._params)[0]
            assert str(next(iter(leaf.devices()))) == rep.device_label
        out, _ = mgr.submit({"data": _rows(2)})
        assert out["prob"].shape == (2, 3)
    finally:
        mgr.shutdown()


def test_fleet_roundtrip_reports_serving_replica():
    """The wire reply names which replica served — the client-visible
    half of the routing story."""
    from poseidon_tpu.proto.wire import recv_frame, send_frame
    import socket as _socket

    from poseidon_tpu.serving.server import InferenceServer

    mgr, _ = _fake_fleet(2)
    srv = InferenceServer(fleet=mgr)
    try:
        sk = _socket.create_connection(srv.addr)
        send_frame(sk, {"kind": "infer",
                        "inputs": {"x": np.ones((1, 2), np.float32)}})
        reply = recv_frame(sk)
        assert reply["ok"] is True
        assert reply["replica"] in (0, 1)
        sk.close()
    finally:
        srv.shutdown()
