"""DiskStreamer analog (data/stream.py) vs the reference's contract:
bounded buffering, multi-pass, snappy mode, end-of-stream signaling."""

import time

import numpy as np
import pytest

from poseidon_tpu.data.libsvm import read_libsvm
from poseidon_tpu.data.stream import (DiskStreamConfig, DiskStreamer,
                                      LibSVMParser, stream_dense_batches)


def _write_libsvm_files(tmp_path, n_files=4, rows_per_file=25, dim=12):
    rs = np.random.RandomState(0)
    rows = []
    for fi in range(n_files):
        lines = []
        for r in range(rows_per_file):
            label = int(rs.randint(0, 2))
            nnz = rs.randint(1, 5)
            idxs = sorted(rs.choice(dim, size=nnz, replace=False))
            toks = " ".join(f"{i + 1}:{(i + 1) * 0.5}" for i in idxs)
            lines.append(f"{label} {toks}")
            rows.append((float(label), idxs))
        (tmp_path / f"part_{fi}").write_text("\n".join(lines) + "\n")
    return rows


def test_streamer_yields_all_records_in_order(tmp_path):
    want = _write_libsvm_files(tmp_path)
    cfg = DiskStreamConfig(file_seq_prefix=str(tmp_path / "part"),
                           num_files=4, num_buffers=2)
    s = DiskStreamer(cfg, LibSVMParser())
    got = []
    while True:
        chunk = s.get_next_data(7)
        if not chunk:
            break
        got.extend(chunk)
    assert len(got) == len(want)
    for (gl, gi, _gv), (wl, wi) in zip(got, want):
        assert gl == wl and list(gi) == list(wi)
    # after EOS, further calls keep returning []
    assert s.get_next_data(1) == []
    s.shutdown()


def test_streamer_multi_pass_and_dir_mode(tmp_path):
    want = _write_libsvm_files(tmp_path, n_files=2, rows_per_file=5)
    cfg = DiskStreamConfig(dir_path=str(tmp_path), num_passes=3)
    s = DiskStreamer(cfg, LibSVMParser())
    n = 0
    while True:
        c = s.get_next_data(64)
        if not c:
            break
        n += len(c)
    assert n == 3 * len(want)
    s.shutdown()


def test_streamer_memory_is_bounded(tmp_path):
    """The IO thread must stall once num_buffers files are in flight —
    the MultiBuffer guarantee that memory stays O(buffers), not O(dataset)."""
    _write_libsvm_files(tmp_path, n_files=6, rows_per_file=10)
    cfg = DiskStreamConfig(dir_path=str(tmp_path), num_buffers=2)
    s = DiskStreamer(cfg, LibSVMParser())
    time.sleep(0.5)  # let the IO thread run ahead as far as it can
    # queue bounded: at most num_buffers buffers ever in flight
    assert s._q.qsize() <= 2
    # and the stream still completes fully
    n = 0
    while True:
        c = s.get_next_data(16)
        if not c:
            break
        n += len(c)
    assert n == 60
    s.shutdown()


def test_streamer_snappy_mode(tmp_path):
    from poseidon_tpu.data.snappy import compress
    raw = b"1 1:0.5 3:1.5\n0 2:2.0\n"
    (tmp_path / "c_0").write_bytes(compress(raw))
    cfg = DiskStreamConfig(file_seq_prefix=str(tmp_path / "c"),
                           num_files=1, snappy_compressed=True)
    s = DiskStreamer(cfg, LibSVMParser())
    rows = s.get_next_data(10)
    assert len(rows) == 2
    assert rows[0][0] == 1.0 and list(rows[0][1]) == [0, 2]
    s.shutdown()


def test_streamer_surfaces_io_errors(tmp_path):
    """A missing/corrupt file must raise on the worker, never silently
    truncate the stream (review finding)."""
    _write_libsvm_files(tmp_path, n_files=1, rows_per_file=3)
    cfg = DiskStreamConfig(file_list=[str(tmp_path / "part_0"),
                                      str(tmp_path / "MISSING")])
    s = DiskStreamer(cfg, LibSVMParser())
    with pytest.raises(RuntimeError, match="IO thread failed"):
        while s.get_next_data(64):
            pass
    s.shutdown()


def test_stream_dense_batches_matches_bulk_reader(tmp_path):
    _write_libsvm_files(tmp_path, n_files=2, rows_per_file=8, dim=10)
    # bulk reference read of the same files
    feats0, labels0 = read_libsvm(str(tmp_path / "part_0"), feature_dim=10)
    cfg = DiskStreamConfig(file_seq_prefix=str(tmp_path / "part"),
                           num_files=2)
    s = DiskStreamer(cfg, LibSVMParser())
    batches = list(stream_dense_batches(s, batch_size=8, feature_dim=10))
    s.shutdown()
    assert sum(b[0].shape[0] for b in batches) == 16
    np.testing.assert_allclose(batches[0][0], feats0.to_dense())
    np.testing.assert_array_equal(batches[0][1], labels0)
