"""Static comm table vs the compiled program's actual collectives.

Round-2 verdict weak #5: the per-layer comm accounting
(runtime/comm_stats.py) was "unvalidated arithmetic" — a static prediction
never reconciled against anything measured. These tests close the loop at
the strongest level available off-hardware: the collectives XLA actually
emitted into the optimized HLO of the compiled train step (payload shapes,
dtypes, replica groups — the compiled data plane itself, fixed at compile
time for SPMD programs).
"""

import jax
import numpy as np
import pytest

from poseidon_tpu.core.net import Net
from poseidon_tpu.models import zoo
from poseidon_tpu.parallel import (CommConfig, SFB, build_train_step,
                                   init_train_state, make_mesh)
from poseidon_tpu.proto.messages import SolverParameter
from poseidon_tpu.runtime.comm_stats import comm_summary, layer_comm_table
from poseidon_tpu.runtime.hlo_comm import (compare_static_vs_measured,
                                           measured_comm_summary,
                                           parse_collectives)

N_DEV = 8
BATCH = 16


@pytest.fixture(scope="module")
def lenet_net():
    return Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
               source_shapes=zoo.lenet_shapes(BATCH // N_DEV))


def _compiled_text(net, comm, mesh):
    import jax.numpy as jnp
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    ts = build_train_step(net, sp, mesh, comm, donate=False)
    params = net.init(jax.random.PRNGKey(0))
    state = init_train_state(params, comm, N_DEV)
    rs = np.random.RandomState(0)
    batch = {"data": jnp.asarray(rs.randn(BATCH, 1, 28, 28)
                                 .astype(np.float32)),
             "label": jnp.asarray(rs.randint(0, 10, size=(BATCH,)))}
    return ts.lowerable.lower(params, state, batch,
                              jax.random.PRNGKey(1)).as_text(), \
        ts.lowerable.lower(params, state, batch,
                           jax.random.PRNGKey(1)).compile().as_text()


def test_dense_static_matches_compiled(lenet_net):
    """DENSE: the static all-reduce bytes must equal what the compiled
    program moves, exactly — same shapes, same ring convention."""
    mesh = make_mesh()
    comm = CommConfig()
    _, hlo = _compiled_text(lenet_net, comm, mesh)
    measured = measured_comm_summary(parse_collectives(hlo))
    static = comm_summary(layer_comm_table(lenet_net, comm, mesh))
    cmp = compare_static_vs_measured(static, measured)
    assert measured["n_collectives"] > 0
    assert cmp["measured_over_static"] == pytest.approx(1.0, abs=1e-3), cmp
    # everything a DENSE step exchanges is an all-reduce
    assert set(measured["by_kind"]) == {"all-reduce"}


def test_sfb_static_matches_compiled(lenet_net):
    """SFB reroutes the FC weight grads into factor all-gathers; static and
    compiled totals must still agree (gathers + remaining psums)."""
    mesh = make_mesh()
    comm = CommConfig(layer_strategies={"ip1": SFB, "ip2": SFB})
    _, hlo = _compiled_text(lenet_net, comm, mesh)
    measured = measured_comm_summary(parse_collectives(hlo))
    static = comm_summary(layer_comm_table(lenet_net, comm, mesh))
    cmp = compare_static_vs_measured(static, measured)
    assert "all-gather" in measured["by_kind"], measured
    assert cmp["measured_over_static"] == pytest.approx(1.0, abs=1e-3), cmp


def test_wire_dtype_visible_in_lowered_program(lenet_net):
    """bf16 wire: the emitted program carries bf16 collectives. Checked on
    the pre-optimization stablehlo (the CPU backend may promote bf16
    reductions back to f32 inside its all-reduce; TPU keeps them)."""
    mesh = make_mesh()
    comm = CommConfig(wire_dtype="bf16")
    stablehlo, _ = _compiled_text(lenet_net, comm, mesh)
    # every gradient psum operand is bf16 in the emitted program
    assert "bf16" in stablehlo
    static = comm_summary(layer_comm_table(lenet_net, comm, mesh))
    f32 = comm_summary(layer_comm_table(lenet_net, CommConfig(), mesh))
    assert static["total_bytes_per_step"] * 2 == \
        f32["total_bytes_per_step"]  # billed at half width


def test_two_tier_groups_parsed(lenet_net):
    """On the (dcn x data) mesh the compiled program's replica groups show
    the tier split; parsed group sizes must reflect it."""
    mesh = make_mesh(axes=("dcn", "data"), shape=(2, 4))
    comm = CommConfig(dcn_axis="dcn", default_strategy="topk",
                      topk_fraction=0.25)
    _, hlo = _compiled_text(lenet_net, comm, mesh)
    colls = [c for c in parse_collectives(hlo)
             if c.payload_bytes >= 16 and c.group_size > 1]
    sizes = {c.group_size for c in colls}
    # intra-slice (4-wide) dense psums AND inter-slice (2-wide) exchanges
    assert 4 in sizes and 2 in sizes, sizes


def test_async_start_tuple_payload_normalization():
    """-start ops carry (operands..., results...); the parser must not
    double-count, and reduce-scatter must bill the FULL input either form."""
    from poseidon_tpu.runtime.hlo_comm import parse_collectives
    hlo = "\n".join([
        # async all-reduce: operand + result (equal) -> payload = one copy
        "%ar = (f32[100]{0}, f32[100]{0}) all-reduce-start(%x), "
        "replica_groups={{0,1,2,3}}, to_apply=%add",
        # sync all-reduce, combined tuple of two results -> payload = sum
        "%arc = (f32[100]{0}, f32[50]{0}) all-reduce(%a, %b), "
        "replica_groups={{0,1,2,3}}, to_apply=%add",
        # async all-gather: operand (1/4) + full result -> payload = full
        "%ag = (f32[25]{0}, f32[100]{0}) all-gather-start(%x), "
        "replica_groups={{0,1,2,3}}, dimensions={0}",
        # sync reduce-scatter: LHS is the SHARD -> payload = shard x n
        "%rs = f32[25]{0} reduce-scatter(%x), "
        "replica_groups={{0,1,2,3}}, dimensions={0}, to_apply=%add",
        # async reduce-scatter: full operand + shard -> payload = full
        "%rs2 = (f32[100]{0}, f32[25]{0}) reduce-scatter-start(%x), "
        "replica_groups={{0,1,2,3}}, dimensions={0}, to_apply=%add",
    ])
    colls = {c.kind + ("_sync" if i in (1, 3) else "_start"): c
             for i, c in enumerate(parse_collectives(hlo))}
    assert colls["all-reduce_start"].payload_bytes == 400
    assert colls["all-reduce_sync"].payload_bytes == 600
    assert colls["all-gather_start"].payload_bytes == 400
    assert colls["reduce-scatter_sync"].payload_bytes == 400
    assert colls["reduce-scatter_start"].payload_bytes == 400
    # wire convention: ar = 2(n-1)/n, ag/rs = (n-1)/n of the full payload
    assert colls["all-reduce_start"].wire_bytes_per_device() == \
        pytest.approx(600.0)
    assert colls["reduce-scatter_sync"].wire_bytes_per_device() == \
        pytest.approx(300.0)


def test_collective_permute_ring_counted():
    """collective-permute carries source_target_pairs, NOT replica_groups;
    before round 5 it fell to group_size=1 and the summary filtered the
    whole ring out — a 16k-token ring-attention capture reported ZERO
    collectives. The ring's bytes must survive into the summary."""
    from poseidon_tpu.runtime.hlo_comm import (measured_comm_summary,
                                               parse_collectives)
    hlo = "\n".join([
        # async permute: (operand, result, u32 contexts) -> payload = one
        "%cp = (bf16[4,256]{1,0}, bf16[4,256]{1,0}, u32[], u32[]) "
        "collective-permute-start(%x), channel_id=1, "
        "source_target_pairs={{0,1},{1,2},{2,3},{3,4},{4,5},{5,6},{6,7},"
        "{7,0}}",
        # sync permute
        "%cp2 = f32[100]{0} collective-permute(%y), "
        "source_target_pairs={{0,1},{1,0}}",
    ])
    colls = parse_collectives(hlo)
    assert len(colls) == 2
    ring, pair = colls
    assert ring.kind == "collective-permute"
    assert ring.group_size == 8          # 8 distinct ring participants
    assert ring.payload_bytes == 4 * 256 * 2 + 4  # one bf16 copy + u32s/2
    assert pair.group_size == 2
    s = measured_comm_summary(colls)
    assert s["n_collectives"] == 2
    assert s["by_kind"]["collective-permute"] > 0
