"""Kernel parity + conv-strategy + bf16 guardrails (the MFU-sink PR).

Three contracts, each pinned against the formulation it replaces:

- **pool backward**: the custom-VJP strategies (Pallas plane kernel in
  interpret mode; vectorized tap-sum) must match the select-and-scatter
  reference arm — f32 tolerance and bf16, both layouts, first-max-wins
  ties included, with the VMEM/taps-cap fallbacks routing safely;
- **LRN**: Pallas fwd+bwd parity vs the XLA formulation in both layouts
  (f32 + bf16) and the routing defaults (XLA off-TPU, Pallas on TPU,
  ``POSEIDON_PALLAS_LRN=0`` opt-out, VMEM-cap fallback);
- **conv strategy**: direct/im2col/s2d lowering parity (fwd + dx/dw, both
  layouts), per-layer measured resolution with persistence through the
  compile-cache tuned store, and the ``--bf16`` LeNet smoke training to a
  loss within ``numeric.BF16_SMOKE_*`` of the f32 run.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu import config
from poseidon_tpu.config import policy_scope
from poseidon_tpu.ops import nn as NN

N_DEV = 8


@pytest.fixture()
def rng_np():
    return np.random.RandomState(0)


@pytest.fixture()
def pool_env(monkeypatch):
    def force(strategy):
        monkeypatch.setenv("POSEIDON_POOL_BWD", strategy)
    return force


POOL_GEOMS = [
    ((3, 3), (2, 2), (0, 0), 9),    # AlexNet-style overlapping pool
    ((3, 3), (2, 2), (1, 1), 8),    # padded + ceil-mode clamp
    ((2, 2), (2, 2), (0, 0), 8),    # LeNet non-overlapping
    ((5, 5), (3, 3), (2, 2), 11),   # larger window, uneven coverage
    ((3, 3), (1, 1), (1, 1), 7),    # stride 1 (the LRN-within path)
]


def _pool_grad(fn, x, k, s, p, layout):
    f = lambda x_: jnp.sum(fn(x_, k, s, p, layout).astype(jnp.float32) ** 2)
    return np.asarray(jax.grad(f)(x))


@pytest.mark.parametrize("method", ["max", "ave"])
@pytest.mark.parametrize("layout", ["NCHW", "NHWC"])
@pytest.mark.parametrize("geom", POOL_GEOMS)
def test_pool_bwd_strategies_match_reference(rng_np, pool_env, method,
                                             layout, geom):
    """taps and (interpret-mode) pallas backward == select-and-scatter."""
    k, s, p, h = geom
    fn = NN.max_pool if method == "max" else NN.ave_pool
    x = rng_np.randn(2, 5, h, h).astype(np.float32)
    if layout == "NHWC":
        x = np.transpose(x, (0, 2, 3, 1)).copy()
    x = jnp.asarray(x)
    pool_env("sas")
    ref = _pool_grad(fn, x, k, s, p, layout)
    for strategy in ("taps", "pallas"):
        pool_env(strategy)
        got = _pool_grad(fn, x, k, s, p, layout)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5,
                                   err_msg=f"{method}/{strategy}/{layout}")


@pytest.mark.parametrize("method", ["max", "ave"])
def test_pool_bwd_bf16(rng_np, pool_env, method):
    """bf16 activations: kernel strategies track the reference within
    bf16 resolution (the kernels recompute/accumulate in f32)."""
    fn = NN.max_pool if method == "max" else NN.ave_pool
    x = jnp.asarray(rng_np.randn(2, 4, 9, 9).astype(np.float32)).astype(
        jnp.bfloat16)
    pool_env("sas")
    ref = _pool_grad(fn, x, (3, 3), (2, 2), (0, 0), "NCHW").astype(
        np.float32)
    for strategy in ("taps", "pallas"):
        pool_env(strategy)
        got = _pool_grad(fn, x, (3, 3), (2, 2), (0, 0), "NCHW").astype(
            np.float32)
        np.testing.assert_allclose(got, ref, rtol=0.05, atol=0.1,
                                   err_msg=f"{method}/{strategy}")


def test_pool_bwd_first_max_wins_ties(pool_env):
    """Constant input: EVERY window position ties, so any argmax
    divergence from Caffe's first-wins `>`-update rule shows up bitwise."""
    x = jnp.ones((1, 3, 8, 8), jnp.float32)
    pool_env("sas")
    ref = _pool_grad(NN.max_pool, x, (3, 3), (2, 2), (1, 1), "NCHW")
    for strategy in ("taps", "pallas"):
        pool_env(strategy)
        got = _pool_grad(NN.max_pool, x, (3, 3), (2, 2), (1, 1), "NCHW")
        np.testing.assert_array_equal(got, ref, err_msg=strategy)


def test_pool_bwd_strategy_routing(monkeypatch):
    from poseidon_tpu.ops.nn import POOL_TAPS_CAP, _pool_bwd_strategy
    monkeypatch.delenv("POSEIDON_POOL_BWD", raising=False)
    # off-TPU default: taps (the CPU thunk-runtime win)
    assert _pool_bwd_strategy((3, 3)) == "taps"
    # a global pool's window exceeds the taps cap: the reference arm
    # (select-and-scatter degenerates to a broadcast there anyway)
    assert _pool_bwd_strategy((9, 9)) == "sas"
    assert 9 * 9 > POOL_TAPS_CAP
    # on-TPU default: the Pallas plane kernel
    monkeypatch.setattr("poseidon_tpu.ops.pallas_kernels._interpret_default",
                        lambda: False)
    assert _pool_bwd_strategy((3, 3)) == "pallas"
    # explicit override always wins
    monkeypatch.setenv("POSEIDON_POOL_BWD", "sas")
    assert _pool_bwd_strategy((3, 3)) == "sas"


def test_pool_plane_feasibility_guard(rng_np, pool_env, monkeypatch):
    """An infeasible plane under forced-pallas must fall back to taps (and
    still be correct), never die in the kernel."""
    from poseidon_tpu.ops.pallas_kernels import pool_plane_feasible
    assert pool_plane_feasible(55, 55, 27, 27, (3, 3))
    assert not pool_plane_feasible(55, 55, 27, 27, (9, 9))   # taps blowup
    assert not pool_plane_feasible(4000, 4000, 2000, 2000, (3, 3))  # VMEM
    x = jnp.asarray(rng_np.randn(1, 2, 9, 9).astype(np.float32))
    pool_env("sas")
    ref = _pool_grad(NN.max_pool, x, (3, 3), (2, 2), (0, 0), "NCHW")
    monkeypatch.setattr("poseidon_tpu.ops.pallas_kernels.pool_plane_feasible",
                        lambda *a: False)
    pool_env("pallas")
    got = _pool_grad(NN.max_pool, x, (3, 3), (2, 2), (0, 0), "NCHW")
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_pool_bwd_under_jit_and_in_net(rng_np, pool_env):
    """The custom VJP composes with jit and a whole-net backward: LeNet
    gradients under taps == under the reference arm."""
    from poseidon_tpu.core.net import Net
    from poseidon_tpu.models import zoo
    net = Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
              source_shapes=zoo.lenet_shapes(4))
    params = net.init(jax.random.PRNGKey(0))
    batch = {"data": jnp.asarray(rng_np.randn(4, 1, 28, 28)
                                 .astype(np.float32)),
             "label": jnp.asarray(rng_np.randint(0, 10, size=(4,)))}

    def loss(p):
        return net.apply(p, batch, rng=jax.random.PRNGKey(1)).loss

    grads = {}
    for strategy in ("sas", "taps"):
        pool_env(strategy)
        jax.clear_caches()     # the strategy is read at trace time
        grads[strategy] = jax.jit(jax.grad(loss))(params)
    for lname in grads["sas"]:
        for pname in grads["sas"][lname]:
            np.testing.assert_allclose(
                np.asarray(grads["taps"][lname][pname]),
                np.asarray(grads["sas"][lname][pname]),
                rtol=1e-5, atol=1e-6, err_msg=f"{lname}/{pname}")


# --------------------------------------------------------------------------- #
# LRN
# --------------------------------------------------------------------------- #

def test_lrn_routing_defaults(monkeypatch):
    """Off-TPU: XLA formulation. On TPU (mocked): Pallas by default,
    POSEIDON_PALLAS_LRN=0 opts out."""
    from poseidon_tpu.ops import pallas_kernels as PK
    x = jnp.ones((1, 4, 4, 4), jnp.float32)
    calls = []
    monkeypatch.setattr(PK, "lrn_fused",
                        lambda *a, **kw: calls.append("pallas") or x)
    monkeypatch.delenv("POSEIDON_PALLAS_LRN", raising=False)
    PK.maybe_lrn_fused(x, 5, 1e-4, 0.75)          # CPU: XLA
    assert calls == []
    monkeypatch.setattr(PK, "_interpret_default", lambda: False)
    PK.maybe_lrn_fused(x, 5, 1e-4, 0.75)          # "TPU": Pallas default
    assert calls == ["pallas"]
    monkeypatch.setenv("POSEIDON_PALLAS_LRN", "0")
    PK.maybe_lrn_fused(x, 5, 1e-4, 0.75)          # opt-out honored
    assert calls == ["pallas"]


@pytest.mark.parametrize("layout", ["NCHW", "NHWC"])
def test_lrn_fwd_bwd_parity_f32(rng_np, layout):
    """Pallas LRN fwd + analytic bwd kernels (interpret mode) vs the XLA
    formulation, through the custom-VJP gradient path."""
    from poseidon_tpu.ops.pallas_kernels import lrn_fused, lrn_fused_bwd
    from poseidon_tpu.ops.nn import lrn_across_channels
    x = rng_np.randn(2, 16, 5, 5).astype(np.float32)
    if layout == "NHWC":
        x = np.transpose(x, (0, 2, 3, 1)).copy()
    xj = jnp.asarray(x)
    want = np.asarray(lrn_across_channels(xj, 5, 1e-4, 0.75, 1.0, layout))
    got = np.asarray(lrn_fused(xj, 5, 1e-4, 0.75, 1.0, layout=layout))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    f_ref = lambda x_: jnp.sum(
        lrn_across_channels(x_, 5, 1e-4, 0.75, 1.0, layout) ** 2)
    dref = np.asarray(jax.grad(f_ref)(xj))
    g = jax.grad(lambda x_: jnp.sum(
        lrn_across_channels(x_, 5, 1e-4, 0.75, 1.0, layout) ** 2))(xj)
    # the standalone analytic backward kernel, driven by the same upstream
    # cotangent the squared-sum loss produces
    y = lrn_across_channels(xj, 5, 1e-4, 0.75, 1.0, layout)
    dk = np.asarray(lrn_fused_bwd(xj, 2.0 * y, 5, 1e-4, 0.75, 1.0,
                                  interpret=True, layout=layout))
    np.testing.assert_allclose(dk, dref, rtol=1e-4, atol=1e-5)
    assert g.shape == xj.shape


@pytest.mark.parametrize("layout", ["NCHW", "NHWC"])
def test_lrn_parity_bf16(rng_np, layout):
    from poseidon_tpu.ops.pallas_kernels import lrn_fused
    from poseidon_tpu.ops.nn import lrn_across_channels
    x = rng_np.randn(2, 16, 5, 5).astype(np.float32)
    if layout == "NHWC":
        x = np.transpose(x, (0, 2, 3, 1)).copy()
    xb = jnp.asarray(x).astype(jnp.bfloat16)
    want = np.asarray(lrn_across_channels(xb, 5, 1e-4, 0.75, 1.0,
                                          layout)).astype(np.float32)
    got = np.asarray(lrn_fused(xb, 5, 1e-4, 0.75, 1.0,
                               layout=layout)).astype(np.float32)
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)


@pytest.mark.parametrize("layout", ["NCHW", "NHWC"])
@pytest.mark.parametrize("local_size,k", [(5, 1.0), (4, 1.5)])
def test_lrn_analytic_xla_bwd_matches_autodiff(rng_np, monkeypatch, layout,
                                               local_size, k):
    """The XLA fallback's analytic custom-VJP backward (what CPU runs by
    default now) == plain autodiff through the forward, odd AND even
    windows, both layouts."""
    from poseidon_tpu.ops.nn import lrn_across_channels
    x = rng_np.randn(2, 16, 4, 4).astype(np.float32)
    if layout == "NHWC":
        x = np.transpose(x, (0, 2, 3, 1)).copy()
    xj = jnp.asarray(x)
    f = lambda x_: jnp.sum(
        lrn_across_channels(x_, local_size, 2e-4, 0.75, k, layout) ** 2)
    monkeypatch.setenv("POSEIDON_LRN_BWD", "autodiff")
    want = np.asarray(jax.grad(f)(xj))
    monkeypatch.delenv("POSEIDON_LRN_BWD")
    got = np.asarray(jax.grad(f)(xj))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_lrn_vmem_cap_falls_back_with_grad():
    """Beyond the ~2560-channel tile cap, lrn_fused silently takes the
    XLA formulation — forward AND backward stay usable."""
    from poseidon_tpu.ops.pallas_kernels import lrn_fused, lrn_tile_feasible
    assert not lrn_tile_feasible(81, 4096)
    x = jnp.ones((1, 4096, 9, 9), jnp.float32)
    y = lrn_fused(x, 5, 1e-4, 0.75)
    g = jax.grad(lambda x_: jnp.sum(lrn_fused(x_, 5, 1e-4, 0.75) ** 2))(x)
    assert y.shape == x.shape and g.shape == x.shape


# --------------------------------------------------------------------------- #
# conv strategies
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("layout", ["NCHW", "NHWC"])
@pytest.mark.parametrize("strategy", ["im2col", "s2d"])
def test_conv_strategy_parity(rng_np, layout, strategy):
    """Every lowering computes the direct conv's numbers (fwd, dx, dw)."""
    x = rng_np.randn(2, 3, 13, 13).astype(np.float32)
    w = rng_np.randn(8, 3, 3, 3).astype(np.float32)
    b = rng_np.randn(8).astype(np.float32)
    if layout == "NHWC":
        x = np.transpose(x, (0, 2, 3, 1)).copy()
    x, w, b = jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)
    args = ((2, 2), (1, 1), 1)

    def run(s):
        y = NN.conv2d(x, w, b, *args, layout=layout, strategy=s)
        f = lambda x_, w_: jnp.sum(
            NN.conv2d(x_, w_, b, *args, layout=layout, strategy=s) ** 2)
        dx, dw = jax.grad(f, argnums=(0, 1))(x, w)
        return map(np.asarray, (y, dx, dw))

    for got, want in zip(run(strategy), run("direct")):
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_conv_strategy_inapplicable_falls_back(rng_np):
    """Grouped conv: im2col/s2d cannot lower it — conv2d silently takes
    direct, and the candidate filter never offers them."""
    x = jnp.asarray(rng_np.randn(1, 4, 8, 8).astype(np.float32))
    w = jnp.asarray(rng_np.randn(8, 2, 3, 3).astype(np.float32))
    want = NN.conv2d(x, w, None, (1, 1), (1, 1), 2, strategy="direct")
    for s in ("im2col", "s2d"):
        got = NN.conv2d(x, w, None, (1, 1), (1, 1), 2, strategy=s)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        assert not NN.conv_strategy_applicable(s, x, w, (1, 1), 2, "NCHW")


def test_conv2d_rejects_unresolved_auto(rng_np):
    x = jnp.zeros((1, 3, 8, 8), jnp.float32)
    w = jnp.zeros((4, 3, 3, 3), jnp.float32)
    with pytest.raises(ValueError, match="auto"):
        NN.conv2d(x, w, None, (1, 1), (0, 0), strategy="auto")


def test_conv_tune_measures_then_persists(tmp_path):
    """First resolve measures and writes the tuned store; a fresh memo
    loads the persisted winner without re-measuring."""
    from poseidon_tpu.ops import conv_tune
    conv_tune.clear_memo()
    kw = dict(c=3, h=9, w=9, kernel=(3, 3), stride=(2, 2), pad=(0, 0),
              group=1, out_ch=4, layout="NCHW", batch=4,
              cache_dir=str(tmp_path))
    doc = conv_tune.resolve("convX", **kw)
    assert doc["source"] == "measured"
    assert doc["winner"] in doc["timings_ms"]
    assert set(doc["timings_ms"]) == {"direct", "im2col", "s2d"}
    assert doc["winner"] == min(doc["timings_ms"],
                                key=doc["timings_ms"].get)
    # memo hit within the process
    assert conv_tune.resolve("convX", **kw)["source"] == "memo"
    # fresh process simulation: memo cleared, store answers
    conv_tune.clear_memo()
    doc3 = conv_tune.resolve("convX", **kw)
    assert doc3["source"] == "persisted"
    assert doc3["winner"] == doc["winner"]
    conv_tune.clear_memo()


def test_conv_tune_single_candidate_skips_measurement(tmp_path):
    from poseidon_tpu.ops import conv_tune
    conv_tune.clear_memo()
    doc = conv_tune.resolve("grouped", c=4, h=8, w=8, kernel=(3, 3),
                            stride=(1, 1), pad=(1, 1), group=2, out_ch=8,
                            layout="NCHW", batch=4,
                            cache_dir=str(tmp_path))
    assert doc == dict(doc, winner="direct", source="only-candidate")
    assert doc["timings_ms"] == {}
    conv_tune.clear_memo()


def test_net_conv_strategy_plumbing(tmp_path):
    """Net-level resolution: a forced strategy lands on every conv layer;
    "auto" assigns each layer a measured winner and a re-built Net (fresh
    memo) loads the persisted choices."""
    from poseidon_tpu.core.net import Net
    from poseidon_tpu.models import zoo
    from poseidon_tpu.ops import conv_tune
    shapes = zoo.lenet_shapes(4)
    net = Net(zoo.lenet(with_accuracy=False), "TRAIN", shapes,
              conv_strategy="im2col")
    assert set(net.conv_strategy_plan().values()) == {"im2col"}
    # legacy default: layers carry None (the global conv_s2d policy rules)
    net0 = Net(zoo.lenet(with_accuracy=False), "TRAIN", shapes)
    assert set(net0.conv_strategy_plan().values()) == {None}
    with pytest.raises(ValueError, match="conv_strategy"):
        Net(zoo.lenet(with_accuracy=False), "TRAIN", shapes,
            conv_strategy="winograd")

    conv_tune.clear_memo()
    saved = config.compile_cache_config().cache_dir
    config.set_compile_cache_config(cache_dir=str(tmp_path))
    try:
        net1 = Net(zoo.lenet(with_accuracy=False), "TRAIN", shapes,
                   conv_strategy="auto")
        plan = net1.conv_strategy_plan()
        assert set(plan) == {"conv1", "conv2"}
        assert all(v in ("direct", "im2col", "s2d")
                   for v in plan.values())
        conv_tune.clear_memo()
        net2 = Net(zoo.lenet(with_accuracy=False), "TRAIN", shapes,
                   conv_strategy="auto")
        assert net2.conv_strategy_plan() == plan
        # the resolved plan actually traces and runs
        params = net1.init(jax.random.PRNGKey(0))
        out = net1.apply(params, {
            "data": jnp.zeros(shapes["data"], jnp.float32),
            "label": jnp.zeros(shapes["label"], jnp.int32)},
            rng=jax.random.PRNGKey(1))
        assert np.isfinite(float(out.loss))
    finally:
        config.set_compile_cache_config(cache_dir=saved)
        conv_tune.clear_memo()


# --------------------------------------------------------------------------- #
# the documented --bf16 path: loss-trajectory guardrail
# --------------------------------------------------------------------------- #

def _train_lenet_losses(rng_np, iters):
    """LeNet overfitting a fixed 4-batch cycle (random labels memorize
    reliably at this lr; fresh batches every step would just bounce)."""
    from poseidon_tpu.core.net import Net
    from poseidon_tpu.models import zoo
    from poseidon_tpu.parallel import (CommConfig, build_train_step,
                                       init_train_state, make_mesh)
    from poseidon_tpu.proto.messages import SolverParameter
    batch_n = 16
    net = Net(zoo.lenet(with_accuracy=False), "TRAIN",
              zoo.lenet_shapes(batch_n // N_DEV))
    sp = SolverParameter(base_lr=0.005, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005)
    cc = CommConfig()
    ts = build_train_step(net, sp, make_mesh(), cc, donate=False)
    params = net.init(jax.random.PRNGKey(0))
    state = init_train_state(params, cc, N_DEV)
    data = rng_np.randn(4, batch_n, 1, 28, 28).astype(np.float32)
    labels = rng_np.randint(0, 10, size=(4, batch_n))
    losses = []
    for i in range(iters):
        batch = {"data": jnp.asarray(data[i % 4]),
                 "label": jnp.asarray(labels[i % 4])}
        params, state, m = ts.step(params, state, batch,
                                   jax.random.fold_in(jax.random.PRNGKey(1),
                                                      i))
        losses.append(float(m["loss"]))
    return losses


def test_bf16_lenet_smoke_within_documented_tolerance():
    """The --bf16 acceptance guardrail: identical data/seeds, f32 vs the
    bf16 perf policy; the end-of-smoke loss level must sit inside the
    documented numeric.BF16_SMOKE_* band. Catches any kernel that starts
    accumulating below f32 where it must not."""
    from poseidon_tpu.numeric import (BF16_SMOKE_ATOL, BF16_SMOKE_ITERS,
                                      BF16_SMOKE_RTOL)
    f32 = _train_lenet_losses(np.random.RandomState(7), BF16_SMOKE_ITERS)
    with policy_scope(compute_dtype=jnp.bfloat16, conv_s2d=True):
        bf16 = _train_lenet_losses(np.random.RandomState(7),
                                   BF16_SMOKE_ITERS)
    assert all(np.isfinite(bf16)), "bf16 run diverged"
    f32_tail = float(np.mean(f32[-5:]))
    bf16_tail = float(np.mean(bf16[-5:]))
    tol = BF16_SMOKE_RTOL * abs(f32_tail) + BF16_SMOKE_ATOL
    assert abs(bf16_tail - f32_tail) <= tol, (
        f"bf16 tail loss {bf16_tail:.4f} drifted beyond the documented "
        f"band from f32 {f32_tail:.4f} (tol {tol:.4f})")
    # and training actually made progress in both arms
    assert f32_tail < float(np.mean(f32[:3]))
    assert bf16_tail < float(np.mean(bf16[:3]))
