"""Managed communication (SSPAggr) for the async-SSP DCN tier (ISSUE 12).

The paper's third signature mechanism: bandwidth-budgeted,
magnitude-prioritized partial pushes that degrade gracefully under network
faults. These tests pin the contract that makes partial pushes SAFE:

1. exactness — a partial push plus its locally-carried residual reassembles
   the update bitwise (sent + residual == delta, elementwise), so at every
   SSP window boundary (the forced full flush) the anchor and every
   worker's applied state are BITWISE identical to the dense path
   (power-of-two deltas make float addition associativity-neutral, the
   PR-6 elasticity idiom);
2. bounded staleness preserved exactly — read gates run on DURABLE
   (fully-flushed) clocks, so a reader never builds on an anchor missing
   bytes the SSP contract promises it;
3. degradation, not divergence — a throttled 3-worker chaos run
   (FaultProxy ``throttle`` + sever/rejoin) completes with loss continuity
   and no gate deadlock;
4. budget = unlimited reduces exactly to today's dense path.

Every socket binds port 0 on loopback — no fixed ports, no flakes.
"""

import socket
import threading
import time

import numpy as np
import pytest

from poseidon_tpu.parallel.async_ssp import (AsyncSSPClient, ParamService,
                                             TokenBucket,
                                             run_async_ssp_worker,
                                             split_topk)
from poseidon_tpu.runtime.faults import FaultProxy, FaultRule

FAST = dict(heartbeat_s=0.1, reconnect_deadline_s=5.0,
            backoff_base_s=0.01, backoff_cap_s=0.1)


def _zeros(shape=(4, 4)):
    return {"fc": {"w": np.zeros(shape, np.float32)}}


def _pow2_delta(worker: int, clock: int, shape=(4, 4)):
    """Deterministic all-power-of-two deltas with DISTINCT magnitudes per
    element (selection is nontrivial) whose running sums are exact in
    float32 — bitwise comparisons then hold under ANY apply order."""
    n = int(np.prod(shape))
    exps = -(np.arange(n) % 6) - clock - 8 * worker
    return {"fc": {"w": (2.0 ** exps).astype(np.float32).reshape(shape)}}


def _drained_client(svc, worker=0, staleness=3, frac=0.25, **kw):
    """Managed client whose bucket is in deep deficit: every non-forced
    push is partial — the deterministic 'budget tight' regime."""
    cli = AsyncSSPClient(worker, ("127.0.0.1", svc.port),
                         staleness=staleness, n_workers=svc.n_workers,
                         budget_mbps=1e-6, priority_frac=frac, **kw)
    cli.budget.consume(1e12)
    return cli


# --------------------------------------------------------------------------- #
# primitives
# --------------------------------------------------------------------------- #

def test_token_bucket_refill_consume_and_cap():
    clk = [0.0]
    b = TokenBucket(rate_bps=100.0, burst_bytes=250.0, clock=lambda: clk[0])
    assert b.available() == 250.0           # starts full
    b.consume(400.0)                        # overdraft is allowed...
    assert b.available() == -150.0          # ...and visible to the policy
    clk[0] = 1.0
    assert b.available() == -50.0           # refills at rate
    clk[0] = 10.0
    assert b.available() == 250.0           # capped at burst
    # default burst floor: tiny configured rates never starve control frames
    assert TokenBucket(rate_bps=1.0).available() >= 65536.0


def test_split_topk_exact_complement_and_budget():
    rs = np.random.RandomState(7)
    tree = {"a": {"w": rs.randn(9, 5).astype(np.float32),
                  "b": rs.randn(7).astype(np.float32)},
            "c": {"w": rs.randn(3, 3).astype(np.float32)}}
    sent, residual, k, n = split_topk(tree, 0.2)
    assert n == 9 * 5 + 7 + 9
    assert k == max(1, int(round(n * 0.2)))
    total_sent = 0
    threshold_sent = np.inf
    threshold_kept = 0.0
    for l, ps in tree.items():
        for p, v in ps.items():
            tag, idx, vals = sent[l][p]
            assert tag == "topk"
            total_sent += idx.size
            dense = np.zeros_like(v)
            dense.flat[idx] += vals
            # THE invariant: sent + residual reassembles the input BITWISE
            assert np.array_equal(dense + residual[l][p], v)
            # selected coordinates leave a zero residual
            assert not np.any(residual[l][p].flat[idx])
            if vals.size:
                threshold_sent = min(threshold_sent, np.abs(vals).min())
            kept = np.abs(residual[l][p])
            if kept.size:
                threshold_kept = max(threshold_kept, kept.max())
    assert total_sent == k
    # magnitude priority is GLOBAL across the tree: nothing kept back
    # outranks anything sent
    assert threshold_kept <= threshold_sent


def test_split_topk_full_fraction_is_dense_copy():
    tree = {"a": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}}
    sent, residual, k, n = split_topk(tree, 1.0)
    assert k == n == 6
    assert np.array_equal(sent["a"]["w"], tree["a"]["w"])
    assert not np.any(residual["a"]["w"])


# --------------------------------------------------------------------------- #
# the acceptance property: bitwise parity at every staleness boundary
# --------------------------------------------------------------------------- #

def test_single_worker_cache_bitwise_equal_to_dense_every_clock():
    """Read-my-writes covers deferred bytes: with one worker, the managed
    cache (anchor + pending + residual) must equal the dense arm's cache
    BITWISE at EVERY clock, not just boundaries — a worker's own view
    never loses what its partial pushes parked."""
    n_clocks, staleness = 8, 3
    dense_svc = ParamService(_zeros(), n_workers=1)
    man_svc = ParamService(_zeros(), n_workers=1)
    dense = AsyncSSPClient(0, ("127.0.0.1", dense_svc.port),
                           staleness=staleness, n_workers=1)
    man = _drained_client(man_svc, staleness=staleness)
    try:
        for c in range(n_clocks):
            d = _pow2_delta(0, c)
            dense.push(d)
            man.push(d)
            dense._drain()
            man._drain()
            cache_d, _ = dense.refresh()
            cache_m, _ = man.refresh()
            assert np.array_equal(cache_d["fc"]["w"], cache_m["fc"]["w"]), c
        assert man.partial_pushes > 0      # deferral actually happened
    finally:
        man.close()
        dense.close()
        man_svc.close()
        dense_svc.close()


def test_boundary_states_bitwise_equal_to_dense_two_workers():
    """THE acceptance test: two workers, managed (finite budget,
    priority_frac < 1) vs dense — at every SSP window boundary the anchor
    AND every worker's applied state at the gate are bitwise identical;
    between boundaries the managed anchor provably lags (deferral is
    real), and the durable clock vector exposes exactly that."""
    n_clocks, staleness = 8, 1          # boundaries at clocks 1, 3, 5, 7
    dense_svc = ParamService(_zeros(), n_workers=2)
    man_svc = ParamService(_zeros(), n_workers=2)
    dense = [AsyncSSPClient(w, ("127.0.0.1", dense_svc.port),
                            staleness=staleness, n_workers=2)
             for w in range(2)]
    man = [_drained_client(man_svc, worker=w, staleness=staleness)
           for w in range(2)]
    deferred_seen = 0
    try:
        for c in range(n_clocks):
            for w in range(2):
                d = _pow2_delta(w, c)
                dense[w].push(d)
                man[w].push(d)
            for w in range(2):
                dense[w]._drain()
                man[w]._drain()
            boundary = (c + 1) % (staleness + 1) == 0
            if boundary:
                # full flush landed: bitwise identical anchors...
                assert np.array_equal(dense_svc.anchor["fc"]["w"],
                                      man_svc.anchor["fc"]["w"]), c
                # ...and durable caught up to the raw clock
                assert man_svc.durable == {0: c, 1: c}
                for w in range(2):
                    # the applied state each worker computes on at its
                    # next gate: refresh()'s read-my-writes cache
                    cache_d, _ = dense[w].refresh()
                    cache_m, _ = man[w].refresh()
                    assert np.array_equal(cache_d["fc"]["w"],
                                          cache_m["fc"]["w"]), (c, w)
                    # the SSP gate itself stays live in both arms
                    assert dense[w].gate(c + 1, timeout_s=10.0) is not None
                    assert man[w].gate(c + 1, timeout_s=10.0) is not None
            else:
                # partial pushes really deferred bytes: the managed anchor
                # lags the dense one mid-window, and durable < raw clock
                if not np.array_equal(dense_svc.anchor["fc"]["w"],
                                      man_svc.anchor["fc"]["w"]):
                    deferred_seen += 1
                assert man_svc.durable[0] < man_svc.clocks[0]
        assert deferred_seen > 0
        assert all(m.partial_pushes > 0 for m in man)
    finally:
        for cli in man + dense:
            cli.close()
        man_svc.close()
        dense_svc.close()


def test_infinite_budget_reduces_exactly_to_dense():
    """budget=None (the default) AND a budget the bucket never exhausts
    must both take the dense path on every push: full flushes only,
    durable == raw clocks, anchors bitwise equal across all three arms
    at EVERY clock."""
    arms = {}
    for name, kw in (("none", {}),
                     ("huge", dict(budget_mbps=1e9, priority_frac=0.1))):
        svc = ParamService(_zeros(), n_workers=1)
        cli = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=3,
                             n_workers=1, **kw)
        arms[name] = (svc, cli)
    try:
        anchors = {}
        for name, (svc, cli) in arms.items():
            for c in range(5):
                cli.push(_pow2_delta(0, c))
            cli._drain()
            assert cli.partial_pushes == 0
            assert cli.full_pushes == 5
            assert cli.comm_counters()["deferred_fraction"] == 0.0
            assert svc.durable == svc.clocks
            anchors[name] = np.array(svc.anchor["fc"]["w"])
        assert np.array_equal(anchors["none"], anchors["huge"])
    finally:
        for svc, cli in arms.values():
            cli.close()
            svc.close()


# --------------------------------------------------------------------------- #
# durable-clock gating: the staleness bound under partial pushes
# --------------------------------------------------------------------------- #

def test_gate_blocks_on_durable_not_raw_clock():
    """A peer whose raw clock ran ahead on PARTIAL pushes must not admit
    a reader: the gate waits for the durable (fully-flushed) clock, and
    unblocks the moment the boundary full flush lands — the exact point
    the anchor actually holds what the SSP contract promises."""
    staleness = 1                        # boundaries at odd clocks
    svc = ParamService(_zeros(), n_workers=2)
    a = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=staleness,
                       n_workers=2)
    b = _drained_client(svc, worker=1, staleness=staleness)
    try:
        b.push(_pow2_delta(1, 0))        # clock 0: partial (non-boundary)
        b._drain()
        assert svc.clocks[1] == 0 and svc.durable[1] == -1
        a.push(_pow2_delta(0, 0))
        a.push(_pow2_delta(0, 1))
        a._drain()
        # reader at clock 2 needs peer durable >= 0; raw clock 0 is NOT
        # enough — the gate must block on the un-flushed residual
        with pytest.raises(TimeoutError):
            a.gate(2, timeout_s=0.6)
        b.push(_pow2_delta(1, 1))        # clock 1: boundary -> full flush
        b._drain()
        assert svc.durable[1] == 1
        a.gate(2, timeout_s=10.0)        # unblocks
    finally:
        b.close()
        a.close()
        svc.close()


def test_residual_flushes_on_mark_done_and_leave():
    """A completed (or deliberately retiring) worker's anchor contribution
    must be its WHOLE update stream — the parked residual flushes before
    'done'/'retire', so bounded loss stays a FAILURE property only."""
    for finisher in ("mark_done", "leave"):
        svc = ParamService(_zeros(), n_workers=1)
        cli = _drained_client(svc, staleness=7)   # boundary far away
        try:
            total = np.zeros((4, 4), np.float32)
            for c in range(3):                    # all partial
                d = _pow2_delta(0, c)
                total += d["fc"]["w"]
                cli.push(d)
            cli._drain()
            assert not np.array_equal(svc.anchor["fc"]["w"], total)
            getattr(cli, finisher)()
            assert np.array_equal(svc.anchor["fc"]["w"], total), finisher
            assert svc.durable[0] == cli.clock
        finally:
            cli.close()
            svc.close()


def test_partial_push_replay_is_exactly_once():
    """Reconnect replay with sparse payloads: the pending oplog holds the
    payload AS SENT, so a severed link replays byte-identical partial
    pushes and the seq dedup keeps the apply exactly-once — the final
    flushed anchor matches the unfaulted dense sum exactly."""
    svc = ParamService(_zeros(), n_workers=1, liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    cli = AsyncSSPClient(0, proxy.addr, staleness=7, n_workers=1,
                         budget_mbps=1e-6, priority_frac=0.25, **FAST)
    cli.budget.consume(1e12)
    try:
        total = np.zeros((4, 4), np.float32)
        d = _pow2_delta(0, 0)
        total += d["fc"]["w"]
        cli.push(d)                       # partial, lands
        cli._drain()
        proxy.sever_all()                 # cut both channels mid-run
        d = _pow2_delta(0, 1)
        total += d["fc"]["w"]
        cli.push(d)                       # partial, rides the replay
        cli._drain(timeout_s=10.0)
        assert cli.reconnects >= 1
        cli.mark_done()                   # residual flush -> exact total
        assert np.array_equal(svc.anchor["fc"]["w"], total)
    finally:
        cli.close()
        proxy.close()
        svc.close()


# --------------------------------------------------------------------------- #
# adaptive cadence
# --------------------------------------------------------------------------- #

def test_adaptive_cadence_backs_off_and_recovers():
    """Congestion (bucket deficit) escalates the payload backoff —
    intermediate clocks ship as empty ticks, counted in cadence_backoffs —
    and a recovered link decays it back toward 1."""
    clk = [0.0]
    svc = ParamService(_zeros(), n_workers=1)
    cli = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=15,
                         n_workers=1, budget_mbps=0.001, priority_frac=0.5,
                         adaptive=True, bucket_clock=lambda: clk[0])
    try:
        cli.budget.consume(1e9)           # deep deficit: congested
        for c in range(3):
            cli.push(_pow2_delta(0, c))
            cli._drain()
        assert cli.cadence_backoffs >= 1
        backed_off = cli.cadence_factor
        assert backed_off > 1
        # deferred ticks: later pushes park the payload locally
        assert cli.partial_pushes >= 1
        clk[0] = 1e13                     # link recovers: bucket refills
        assert cli.budget.available() > 0
        for c in range(8):
            cli.push(_pow2_delta(0, 3 + c))
            cli._drain()
        assert cli.cadence_factor < backed_off
        cli.mark_done()                   # residual still lands in full
        assert svc.durable[0] == cli.clock
    finally:
        cli.close()
        svc.close()


# --------------------------------------------------------------------------- #
# telemetry plumbing
# --------------------------------------------------------------------------- #

def test_comm_counters_shape_and_formatting():
    from poseidon_tpu.runtime.comm_stats import (format_comm,
                                                 managed_comm_counters)
    svc = ParamService(_zeros(), n_workers=1)
    cli = _drained_client(svc, staleness=3)
    try:
        for c in range(4):                # 3 partial + 1 boundary full
            cli.push(_pow2_delta(0, c))
        cli._drain()
        cc = managed_comm_counters(cli)
        for key in ("bytes_sent", "bytes_recv", "deferred_fraction",
                    "effective_mbps", "cadence_backoffs",
                    "partial_pushes", "full_pushes"):
            assert key in cc, key
        assert cc["bytes_sent"] > 0 and cc["bytes_recv"] > 0
        assert 0.0 < cc["deferred_fraction"] < 1.0
        assert cc["partial_pushes"] == 3 and cc["full_pushes"] == 1
        line = format_comm(cc)
        assert "deferred_fraction" in line and "bytes_sent" in line
        # no client (sync tiers): empty, and the display line degrades
        assert managed_comm_counters(None) == {}
    finally:
        cli.close()
        svc.close()


def test_managed_comm_config_defaults_resolve_into_tier(monkeypatch):
    """`config.set_managed_comm_config` is what None-valued tier knobs
    resolve against (the FaultConfig pattern), and explicit tier knobs
    win over it."""
    from poseidon_tpu import config
    from poseidon_tpu.runtime.async_tier import AsyncSSPTier

    monkeypatch.setenv("POSEIDON_PROC_ID", "0")
    monkeypatch.setenv("POSEIDON_NUM_PROCS", "1")
    monkeypatch.delenv("POSEIDON_COORDINATOR", raising=False)
    defaults = config.ManagedCommConfig()
    config.set_managed_comm_config(budget_mbps=5.0, priority_frac=0.2,
                                   adaptive=True)
    try:
        tier = AsyncSSPTier(_zeros(), staleness=2, service_port=0)
        try:
            assert tier.comm_budget_mbps == 5.0
            assert tier.client.budget is not None
            assert tier.client.priority_frac == 0.2
            assert tier.client.adaptive is True
        finally:
            tier.client._stop.set()
            tier.service.close()
        tier2 = AsyncSSPTier(_zeros(), staleness=2, service_port=0,
                             comm_budget_mbps=0.0)   # explicit: unlimited
        try:
            assert tier2.client.budget is None
        finally:
            tier2.client._stop.set()
            tier2.service.close()
        with pytest.raises(AttributeError):
            config.set_managed_comm_config(no_such_knob=1.0)
    finally:
        config.set_managed_comm_config(
            budget_mbps=defaults.budget_mbps,
            priority_frac=defaults.priority_frac,
            adaptive=defaults.adaptive)


def test_full_fraction_partial_is_labeled_full():
    """priority_frac=1.0 (or a tree tiny enough that the 1-entry floor
    selects everything) ships the whole update — that IS a full flush and
    must be labeled one: durable advances every clock, no all-zero
    residual is carried, no phantom 'partial' telemetry."""
    svc = ParamService(_zeros(), n_workers=1)
    cli = _drained_client(svc, staleness=7, frac=1.0)
    try:
        for c in range(3):                # all non-boundary clocks
            cli.push(_pow2_delta(0, c))
        cli._drain()
        assert cli.partial_pushes == 0
        assert cli.full_pushes == 3
        assert not cli._has_residual()
        assert svc.durable[0] == 2        # durable tracks every clock
    finally:
        cli.close()
        svc.close()


def test_adarevision_refuses_managed_budget():
    svc = ParamService(_zeros(), n_workers=1, server_logic="adarevision")
    try:
        with pytest.raises(ValueError, match="adarevision"):
            AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=1,
                           n_workers=1, server_logic="adarevision",
                           budget_mbps=1.0)
    finally:
        svc.close()


# --------------------------------------------------------------------------- #
# the chaos acceptance: throttled 3-worker run with sever/rejoin
# --------------------------------------------------------------------------- #

def test_throttled_three_worker_chaos_keeps_gates_live():
    """The robustness acceptance: 3 managed workers through a FaultProxy
    shaping every connection to a slow link (throttle), with a full
    mid-run partition (sever_all) forcing reconnect + replay. The run
    must complete — no gate deadlock — with loss continuity (every
    worker reports every clock) and the final anchor holding EXACTLY the
    full update mass (integer-valued deltas: bitwise-checkable)."""
    n_workers, n_clocks, staleness = 3, 5, 2
    # 128x128 f32 = 64 kB dense — bigger than the client bucket's burst
    # floor, so the first dense flush drives the budget into deficit and
    # every non-boundary flush after it is a cheap partial push
    params = {"fc": {"w": np.zeros((128, 128), np.float32)}}
    svc = ParamService(params, n_workers=n_workers,
                       liveness_timeout_s=5.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    # every connection rides a ~80 kB/s link: dense flushes crawl (~0.8 s
    # each), partial pushes stay cheap (~0.1 s)
    proxy.add_rule(FaultRule(action="throttle", rate_bps=80_000,
                             burst_bytes=16_384))

    def step(worker):
        def fn(p, it):
            out = {l: {pn: v + 1.0 for pn, v in ps.items()}
                   for l, ps in p.items()}
            return out, float(out["fc"]["w"].mean())
        return fn

    results = [None] * n_workers
    errs = []

    def go(w):
        try:
            results[w] = run_async_ssp_worker(
                w, n_workers, params, step(w), n_clocks, staleness,
                service_addr=proxy.addr, sync_every=1,
                client_opts=dict(budget_mbps=0.64, priority_frac=0.1,
                                 **FAST))
        except Exception as e:  # noqa: BLE001
            errs.append((w, e))

    ts = [threading.Thread(target=go, args=(w,)) for w in range(n_workers)]
    try:
        for t in ts:
            t.start()
        time.sleep(1.0)                   # mid-run: hard partition
        cut = proxy.sever_all()
        assert cut > 0, "sever fired after the run ended (retune timings)"
        for t in ts:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in ts), "gate deadlock"
        assert not errs, errs
        # loss continuity: every worker reports EVERY clock's loss
        for w, res in enumerate(results):
            assert len(res["losses"]) == n_clocks, (w, res["losses"])
            assert res["final_clock"] >= n_clocks - 1
        # the partition was real: somebody reconnected and replayed
        assert sum(r["reconnects"] for r in results) >= 1
        # partial pushes actually happened (budget in deficit after the
        # first dense flush), yet exactness held: +1-everywhere deltas
        # are integers — the anchor must hold the complete update mass,
        # partials + residual flushes + replays notwithstanding
        assert np.array_equal(
            svc.anchor["fc"]["w"],
            np.full((128, 128), float(n_workers * n_clocks), np.float32))
        # the SSP bound held through the chaos
        assert svc.max_spread <= staleness + 1
    finally:
        proxy.close()
        svc.close()
