"""Multi-step-per-dispatch (scan_steps): K optimizer steps in one program.

The round-3 hardware window proved the runtime's per-dispatch round-trip
(~720 ms through the axon tunnel) dwarfs the 34 ms device step, so the
trainer grew a lax.scan-over-steps mode. Invariant: scan_steps=K runs the
same math as K sequential single-step dispatches fed the same microbatches
and rng stream — equal up to compilation-order float rounding (the two
programs fuse differently), so parameters are compared at tight tolerance,
not bit equality.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.core.net import Net
from poseidon_tpu.models import zoo
from poseidon_tpu.parallel import (
    CommConfig, SFB, TOPK, build_train_step, init_train_state, make_mesh,
    stack_batches)
from poseidon_tpu.proto.messages import SolverParameter

N_DEV = 8
BATCH = 16
K = 4


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() == N_DEV
    return make_mesh()


@pytest.fixture(scope="module")
def net():
    return Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
               source_shapes=zoo.lenet_shapes(BATCH // N_DEV))


def _batches(rng, k=K):
    return [{
        "data": rng.randn(BATCH, 1, 28, 28).astype(np.float32),
        "label": rng.randint(0, 10, size=(BATCH,)),
    } for _ in range(k)]


def _sp():
    return SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                           weight_decay=0.0005)


@pytest.mark.parametrize("comm_kw", [
    {},
    {"layer_strategies": {"ip1": SFB}},
    {"layer_strategies": {"ip2": TOPK}, "topk_fraction": 0.25},
])
def test_scan_matches_sequential(mesh, net, rng_np, comm_kw):
    comm = CommConfig(**comm_kw)
    params = net.init(jax.random.PRNGKey(0))
    batches = _batches(rng_np)
    rng = jax.random.PRNGKey(7)

    # sequential single-step dispatches, rng folded per step like scan does
    ts1 = build_train_step(net, _sp(), mesh, comm, donate=False)
    p, s = params, init_train_state(params, comm, N_DEV)
    losses = []
    for i, b in enumerate(batches):
        b = {k: jnp.asarray(v) for k, v in b.items()}
        p, s, m = ts1.step(p, s, b, jax.random.fold_in(rng, i))
        losses.append(float(m["loss"]))

    tsk = build_train_step(net, _sp(), mesh, comm, donate=False,
                           scan_steps=K)
    assert tsk.scan_steps == K
    stacked = stack_batches(batches, tsk.batch_sharding)
    assert stacked["data"].shape == (K, BATCH, 1, 28, 28)
    pk, sk, mk = tsk.step(params, init_train_state(params, comm, N_DEV),
                          stacked, rng)

    assert mk["loss"].shape == (K,)
    np.testing.assert_allclose(np.asarray(mk["loss"]), losses, rtol=1e-6)
    # same math, but scan-compiled vs per-step-compiled programs may fuse
    # (and so round) differently — tight tolerance, not bit equality
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-6),
        p, pk)
    np.testing.assert_array_equal(np.asarray(s.solver.it),
                                  np.asarray(sk.solver.it))


def test_scan_on_two_tier_mesh(net, rng_np):
    from jax.sharding import Mesh
    devs = np.array(jax.devices()).reshape(2, 4)
    mesh = Mesh(devs, ("dcn", "data"))
    comm = CommConfig(dcn_axis="dcn",
                      layer_strategies={"ip2": TOPK}, topk_fraction=0.25)
    params = net.init(jax.random.PRNGKey(0))
    from poseidon_tpu.parallel import comm_error_groups
    tsk = build_train_step(net, _sp(), mesh, comm, donate=False,
                           scan_steps=K)
    stacked = stack_batches(_batches(rng_np), tsk.batch_sharding)
    state0 = init_train_state(params, comm, comm_error_groups(comm, mesh))
    pk, sk, mk = tsk.step(params, state0, stacked, jax.random.PRNGKey(7))
    assert mk["loss"].shape == (K,)
    assert np.isfinite(np.asarray(mk["loss"])).all()
    assert int(sk.solver.it) == K


def test_scan_rejects_dump_blobs(mesh, net):
    with pytest.raises(ValueError, match="scan_steps"):
        build_train_step(net, _sp(), mesh, CommConfig(), scan_steps=2,
                         dump_blobs=["ip1"])


def test_scan_reuse_batch_matches_repeated_batch(mesh, net, rng_np):
    """scan_reuse_batch=True == scan over K copies of the same batch: same
    final params, same per-step losses, one on-device batch."""
    comm = CommConfig()
    params = net.init(jax.random.PRNGKey(0))
    one = _batches(rng_np, k=1)[0]
    rng = jax.random.PRNGKey(7)

    tsk = build_train_step(net, _sp(), mesh, comm, donate=False,
                           scan_steps=K)
    stacked = stack_batches([one] * K, tsk.batch_sharding)
    pk, sk, mk = tsk.step(params, init_train_state(params, comm, N_DEV),
                          stacked, rng)

    tsr = build_train_step(net, _sp(), mesh, comm, donate=False,
                           scan_steps=K, scan_reuse_batch=True)
    single = {k: jax.device_put(jnp.asarray(v), tsr.batch_sharding)
              for k, v in one.items()}
    assert single["data"].shape == (BATCH, 1, 28, 28)  # no [K] axis
    pr, sr, mr = tsr.step(params, init_train_state(params, comm, N_DEV),
                          single, rng)

    assert mr["loss"].shape == (K,)
    np.testing.assert_allclose(np.asarray(mr["loss"]),
                               np.asarray(mk["loss"]), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-6),
        pr, pk)
    # params actually evolved (it's K optimizer steps, not one)
    assert int(sr.solver.it) == K
