"""Pallas kernels (interpret mode on CPU) vs reference ops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.ops.attention import attention
from poseidon_tpu.ops.nn import lrn_across_channels
from poseidon_tpu.ops.pallas_kernels import flash_attention, lrn_fused

B, H, S, D = 2, 3, 128, 32


@pytest.fixture(scope="module")
def qkv():
    rs = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rs.randn(B, H, S, D).astype(np.float32) * 0.3)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(qkv, causal):
    q, k, v = qkv
    want = attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal, None, 32, 64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_gradients(qkv):
    q, k, v = qkv

    def loss_ref(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True, None, 32, 32) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gf, "qkv"):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-3, atol=5e-4, err_msg=name)


def test_lrn_fused_matches_reference():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(2, 16, 8, 8).astype(np.float32))
    want = lrn_across_channels(x, 5, 1e-4, 0.75)
    got = lrn_fused(x, 5, 1e-4, 0.75)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
