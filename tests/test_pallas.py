"""Pallas kernels (interpret mode on CPU) vs reference ops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.ops.attention import attention
from poseidon_tpu.ops.nn import lrn_across_channels
from poseidon_tpu.ops.pallas_kernels import flash_attention, lrn_fused

B, H, S, D = 2, 3, 128, 32


@pytest.fixture(scope="module")
def qkv():
    rs = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rs.randn(B, H, S, D).astype(np.float32) * 0.3)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(qkv, causal):
    q, k, v = qkv
    want = attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal, None, 32, 64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("blocks", [(32, 32), (32, 64), (128, 128)])
def test_flash_attention_gradients(qkv, causal, blocks):
    """The Pallas dq/dk/dv kernels (O(S) memory, recompute-from-lse) against
    the dense reference VJP, across block shapes incl. full-sequence tiles."""
    q, k, v = qkv
    bq, bk = blocks

    def loss_ref(q, k, v):
        return jnp.sum(attention(q, k, v, causal=causal) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, None, bq, bk) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gf, "qkv"):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-3, atol=5e-4, err_msg=name)


def test_flash_attention_grad_under_jit_and_vmapless_batch(qkv):
    """Backward works inside jit (the training-path usage)."""
    q, k, v = qkv
    f = jax.jit(jax.grad(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, True, None, 32, 32)
        .sum(), argnums=(0, 1, 2)))
    gq, gk, gv = f(q, k, v)
    for g in (gq, gk, gv):
        assert np.isfinite(np.asarray(g)).all()


def test_pick_block_non_power_of_two_lengths():
    """Non-power-of-two sequence lengths must tile with the largest
    ALIGNED block that divides them (Mosaic needs the second-minor block
    dim to be a multiple of the 8-row f32 sublane tile), not fall back to
    None — s=48 tiles at 16, s=136 at 8; only unaligned lengths refuse."""
    from poseidon_tpu.ops.pallas_kernels import pick_block
    assert pick_block(1024) == 128
    assert pick_block(384) == 128     # 3 * 128
    assert pick_block(96) == 32
    assert pick_block(48) == 16       # used to fall back to None
    assert pick_block(136) == 8       # 17 * 8
    assert pick_block(24) == 8
    assert pick_block(100) is None    # 4 mod 8: no aligned block exists
    assert pick_block(7) is None
    # the flash kernel really runs at the small-block rungs
    rs = np.random.RandomState(3)
    q = jnp.asarray(rs.randn(1, 2, 48, 16).astype(np.float32))
    from poseidon_tpu.ops.pallas_kernels import flash_attention
    got = flash_attention(q, q, q, True, None, pick_block(48),
                          pick_block(48), interpret=True)
    want = attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_maybe_flash_routing(qkv):
    """Off-TPU, routing must use the dense op (interpret-mode Pallas would
    be an emulation slowdown) — bit-identical to attention(). On a real TPU
    (POSEIDON_TEST_TPU=1 runs), routing takes the Mosaic-compiled flash
    kernel instead — numerically close, not bitwise."""
    from poseidon_tpu.ops.pallas_kernels import maybe_flash_attention
    q, k, v = qkv
    got = maybe_flash_attention(q, k, v, causal=True)
    want = attention(q, k, v, causal=True)
    if jax.default_backend() == "tpu":
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)
    else:
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_lrn_fused_matches_reference():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(2, 16, 8, 8).astype(np.float32))
    want = lrn_across_channels(x, 5, 1e-4, 0.75)
    got = lrn_fused(x, 5, 1e-4, 0.75)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_lrn_fused_gradient_matches_reference():
    """The recompute VJP: grad through the Pallas forward must equal grad
    through the XLA formulation (it literally recomputes through it)."""
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(2, 16, 6, 6).astype(np.float32))
    g_ref = jax.grad(
        lambda x_: jnp.sum(lrn_across_channels(x_, 5, 1e-4, 0.75) ** 2))(x)
    g_fused = jax.grad(
        lambda x_: jnp.sum(lrn_fused(x_, 5, 1e-4, 0.75) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_fused), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


def test_lrn_fused_bwd_kernel_matches_analytic():
    """The one-pass Pallas backward (interpret mode here, Mosaic on chip)
    must reproduce the autodiff gradient of the XLA formulation — the
    analytic Caffe gradient with the mirrored transpose window
    (lrn_layer.cpp CrossChannelBackward)."""
    from poseidon_tpu.ops.pallas_kernels import lrn_fused_bwd
    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(2, 16, 6, 6).astype(np.float32))
    g = jnp.asarray(rs.randn(2, 16, 6, 6).astype(np.float32))
    _, vjp = jax.vjp(
        lambda x_: lrn_across_channels(x_, 5, 1e-4, 0.75), x)
    (want,) = vjp(g)
    got = lrn_fused_bwd(x, g, 5, 1e-4, 0.75, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_lrn_fused_bwd_kernel_even_window():
    """Asymmetric (even) local_size exercises the mirrored pre/post pads."""
    from poseidon_tpu.ops.pallas_kernels import lrn_fused_bwd
    rs = np.random.RandomState(5)
    x = jnp.asarray(rs.randn(1, 12, 4, 4).astype(np.float32))
    g = jnp.asarray(rs.randn(1, 12, 4, 4).astype(np.float32))
    _, vjp = jax.vjp(
        lambda x_: lrn_across_channels(x_, 4, 2e-4, 0.9, 1.5), x)
    (want,) = vjp(g)
    got = lrn_fused_bwd(x, g, 4, 2e-4, 0.9, 1.5, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_maybe_lrn_fused_routing():
    """Default routing is the XLA formulation EVERYWHERE — the round-5
    cost-model A/B retired the Pallas default (its boundary copies cost
    more than the fused XLA chain; evidence/aot_tpu/layer_cycles.json).
    POSEIDON_PALLAS_LRN=1 opts back in on TPU; the kernel itself is
    covered by the interpret-mode tests above and the Mosaic AOT gate
    (tests/test_aot_tpu.py) — it cannot EXECUTE on the CPU runtime."""
    from poseidon_tpu.ops.pallas_kernels import maybe_lrn_fused
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(1, 8, 5, 5).astype(np.float32))
    want = lrn_across_channels(x, 5, 1e-4, 0.75)
    got = maybe_lrn_fused(x, 5, 1e-4, 0.75)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_lrn_fused_nhwc_entry_matches_nchw():
    """The NHWC kernel entry (net-level channels-last plan): channels on
    the MINOR axis inside the block, no layout round-trip at the
    custom-call boundary — same numbers as the NCHW kernel."""
    rs = np.random.RandomState(6)
    x = rs.randn(2, 16, 8, 8).astype(np.float32)
    xt = jnp.asarray(np.transpose(x, (0, 2, 3, 1)).copy())
    want = np.asarray(lrn_fused(jnp.asarray(x), 5, 1e-4, 0.75))
    got = np.asarray(lrn_fused(xt, 5, 1e-4, 0.75, layout="NHWC"))
    np.testing.assert_allclose(np.transpose(got, (0, 3, 1, 2)), want,
                               rtol=1e-5, atol=1e-6)


def test_lrn_fused_bwd_nhwc_matches_analytic():
    from poseidon_tpu.ops.pallas_kernels import lrn_fused_bwd
    rs = np.random.RandomState(7)
    x = rs.randn(2, 16, 6, 6).astype(np.float32)
    g = rs.randn(2, 16, 6, 6).astype(np.float32)
    _, vjp = jax.vjp(
        lambda x_: lrn_across_channels(x_, 5, 1e-4, 0.75), jnp.asarray(x))
    (want,) = vjp(jnp.asarray(g))
    got = lrn_fused_bwd(jnp.asarray(np.transpose(x, (0, 2, 3, 1)).copy()),
                        jnp.asarray(np.transpose(g, (0, 2, 3, 1)).copy()),
                        5, 1e-4, 0.75, interpret=True, layout="NHWC")
    np.testing.assert_allclose(
        np.transpose(np.asarray(got), (0, 3, 1, 2)), np.asarray(want),
        rtol=1e-5, atol=1e-6)


def test_lrn_tile_rejects_vmem_busting_channel_counts():
    """Advisor finding (round 6): at channels > ~2560 the VMEM budget caps
    the spatial tile below 128 lanes; _lrn_tile must refuse (clear error)
    instead of emitting a block that exceeds the scoped-VMEM limit at
    Mosaic compile time."""
    import pytest as _pytest
    from poseidon_tpu.ops.pallas_kernels import (LRNTileError, _lrn_tile,
                                                 lrn_tile_feasible)
    # comfortably feasible: the AlexNet/GoogLeNet norms
    assert lrn_tile_feasible(55 * 55, 96)
    assert lrn_tile_feasible(56 * 56, 192)
    # the cap boundary: budget/(4*8*128) = 2560 channels
    assert lrn_tile_feasible(128 * 128, 2560)
    assert not lrn_tile_feasible(128 * 128, 2561)
    assert not lrn_tile_feasible(128 * 128, 4096)
    with _pytest.raises(LRNTileError, match="XLA formulation"):
        _lrn_tile(128 * 128, 512, 4096)


def test_lrn_fused_falls_back_to_xla_above_tile_cap():
    """lrn_fused at 4096 channels (no legal tile) must silently take the
    XLA formulation — same numbers, forward and gradient, no Mosaic
    blowup."""
    rs = np.random.RandomState(8)
    # hw must exceed the budget's full-extent fit (hw > ~80 at 4096ch) so
    # the tiler is actually consulted — and then refuses (cap 80 < 128)
    x = jnp.asarray(rs.randn(1, 4096, 12, 12).astype(np.float32))
    want = lrn_across_channels(x, 5, 1e-4, 0.75)
    got = lrn_fused(x, 5, 1e-4, 0.75)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    g_want = jax.grad(
        lambda x_: jnp.sum(lrn_across_channels(x_, 5, 1e-4, 0.75) ** 2))(x)
    g_got = jax.grad(
        lambda x_: jnp.sum(lrn_fused(x_, 5, 1e-4, 0.75) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want),
                               rtol=1e-5, atol=1e-6)
