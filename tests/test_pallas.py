"""Pallas kernels (interpret mode on CPU) vs reference ops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu.ops.attention import attention
from poseidon_tpu.ops.nn import lrn_across_channels
from poseidon_tpu.ops.pallas_kernels import flash_attention, lrn_fused

B, H, S, D = 2, 3, 128, 32


@pytest.fixture(scope="module")
def qkv():
    rs = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rs.randn(B, H, S, D).astype(np.float32) * 0.3)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_reference(qkv, causal):
    q, k, v = qkv
    want = attention(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal, None, 32, 64)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("blocks", [(32, 32), (32, 64), (128, 128)])
def test_flash_attention_gradients(qkv, causal, blocks):
    """The Pallas dq/dk/dv kernels (O(S) memory, recompute-from-lse) against
    the dense reference VJP, across block shapes incl. full-sequence tiles."""
    q, k, v = qkv
    bq, bk = blocks

    def loss_ref(q, k, v):
        return jnp.sum(attention(q, k, v, causal=causal) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal, None, bq, bk) ** 2)

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gf, "qkv"):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-3, atol=5e-4, err_msg=name)


def test_flash_attention_grad_under_jit_and_vmapless_batch(qkv):
    """Backward works inside jit (the training-path usage)."""
    q, k, v = qkv
    f = jax.jit(jax.grad(
        lambda q_, k_, v_: flash_attention(q_, k_, v_, True, None, 32, 32)
        .sum(), argnums=(0, 1, 2)))
    gq, gk, gv = f(q, k, v)
    for g in (gq, gk, gv):
        assert np.isfinite(np.asarray(g)).all()


def test_maybe_flash_falls_back_off_tpu(qkv):
    """Off-TPU routing must use the dense op (interpret-mode Pallas would be
    an emulation slowdown), bit-identical to attention()."""
    from poseidon_tpu.ops.pallas_kernels import maybe_flash_attention
    q, k, v = qkv
    got = maybe_flash_attention(q, k, v, causal=True)
    want = attention(q, k, v, causal=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_lrn_fused_matches_reference():
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(2, 16, 8, 8).astype(np.float32))
    want = lrn_across_channels(x, 5, 1e-4, 0.75)
    got = lrn_fused(x, 5, 1e-4, 0.75)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)
