"""Protocol verifier tests (ISSUE 15): wire-schema lint (PROTO2xx),
bounded model checking of the SSP/managed-comm protocol, trace
conformance against the real tier, and the CLI exit-code contract.

Structure mirrors tests/test_analysis.py: every PROTO rule fires on a
fixture snippet and stays quiet on its well-formed twin; the model
checker's explored-state counts are pinned exactly (a model edit that
silently prunes interleavings must show up as a count change); every
seeded mutation MUST be caught (a mutation the checker agrees with is a
checker regression); and a real 2-worker managed-communication run with
elastic admit + retire replays cleanly through the model's service
rules.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from poseidon_tpu.analysis import filter_new, load_baseline
from poseidon_tpu.analysis import model_check as M
from poseidon_tpu.analysis import protocol as P

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------------------- #
# fixtures: a minimal service + client pair the extractor understands
# --------------------------------------------------------------------------- #

DISPATCHER_OK = '''
def recv_frame(conn): ...
def send_frame(conn, obj): ...
def server_handshake(conn, token): ...

class Service:
    def _serve(self, conn):
        if self.token is not None:
            if not server_handshake(conn, self.token):
                return
        while True:
            msg = recv_frame(conn)
            kind = msg["kind"]
            if kind == "ping":
                send_frame(conn, {"ok": True})
            elif kind == "put":
                w = msg["worker"]
                seq = msg.get("seq", msg["clock"])
                self.table[w] += msg["delta"]
                send_frame(conn, {"ok": True, "applied": seq})
            elif kind == "get":
                send_frame(conn, {"ok": True, "value": self.table})
'''

CLIENT_OK = '''
def send_frame(sock, obj): ...
def recv_frame(sock): ...

class Client:
    def _rpc(self, msg):
        send_frame(self._sock, msg)
        return recv_frame(self._sock)

    def ping(self):
        return self._rpc({"kind": "ping"})

    def put(self, delta):
        self._rpc({"kind": "put", "worker": self.w, "clock": self.c,
                   "seq": self.c, "delta": delta})

    def get(self):
        reply = self._rpc({"kind": "get"})
        return reply["value"]
'''


def _spec(tmp_path, dispatcher_src, client_src, **kw):
    d = tmp_path / "svc.py"
    c = tmp_path / "cli.py"
    d.write_text(textwrap.dedent(dispatcher_src))
    c.write_text(textwrap.dedent(client_src))
    return P.ServiceSpec(name="fixture",
                         dispatcher=(str(d), "Service", "_serve"),
                         recv_method="_serve",
                         sender_files=(str(c),), **kw)


def _findings(tmp_path, dispatcher_src, client_src, **kw):
    _, fs = P.extract_service(_spec(tmp_path, dispatcher_src, client_src,
                                    **kw))
    return fs


def _rules(findings):
    return sorted({f.rule for f in findings})


def test_well_formed_pair_is_quiet(tmp_path):
    assert _findings(tmp_path, DISPATCHER_OK, CLIENT_OK) == []


def test_schema_extraction_shape(tmp_path):
    schema, _ = P.extract_service(_spec(tmp_path, DISPATCHER_OK, CLIENT_OK))
    assert set(schema["kinds"]) == {"ping", "put", "get"}
    put = schema["kinds"]["put"]
    assert put["required_fields"] == ["clock", "delta", "worker"]
    assert put["optional_fields"] == ["seq"]
    assert put["mutating"] is True            # self.table[w] += delta
    assert schema["kinds"]["ping"]["mutating"] is False
    assert schema["kinds"]["get"]["reply_keys"] == ["ok", "value"]
    assert "value" in schema["kinds"]["get"]["client_reads"]


def test_proto201_sent_but_unhandled(tmp_path):
    bad = CLIENT_OK + '''
    def stats(self):
        return self._rpc({"kind": "stats"})
'''
    fs = _findings(tmp_path, DISPATCHER_OK, bad)
    assert _rules(fs) == ["PROTO201"]
    assert fs[0].key == "kind:stats"


def test_proto202_handled_but_never_sent(tmp_path):
    bad = DISPATCHER_OK + '''\
            elif kind == "flush":
                send_frame(conn, {"ok": True})
'''
    fs = _findings(tmp_path, bad, CLIENT_OK)
    assert _rules(fs) == ["PROTO202"]
    # ...and declaring it external ops vocabulary silences it
    assert _findings(tmp_path, bad, CLIENT_OK,
                     external_kinds=("flush",)) == []


def test_proto203_required_field_missing_from_sender(tmp_path):
    bad_d = DISPATCHER_OK.replace(
        'self.table[w] += msg["delta"]',
        'self.table[w] += msg["delta"] * msg["scale"]')
    fs = _findings(tmp_path, bad_d, CLIENT_OK)
    assert "PROTO203" in _rules(fs)
    assert any(f.key == "put.scale" for f in fs)


def test_proto204_reply_key_never_produced(tmp_path):
    bad_c = CLIENT_OK.replace('return reply["value"]',
                              'return reply["valeu"]')
    fs = _findings(tmp_path, DISPATCHER_OK, bad_c)
    assert _rules(fs) == ["PROTO204"]
    assert fs[0].key == "get.reply.valeu"
    # a .get() read of the same missing key is the caller's explicit
    # default — no finding
    ok_c = CLIENT_OK.replace('return reply["value"]',
                             'return reply.get("valeu")')
    assert _findings(tmp_path, DISPATCHER_OK, ok_c) == []


def test_proto205_unpickle_before_auth_and_no_auth(tmp_path):
    # handshake AFTER the first frame parse
    reordered = '''
    def recv_frame(conn): ...
    def send_frame(conn, obj): ...
    def server_handshake(conn, token): ...

    class Service:
        def _serve(self, conn):
            msg = recv_frame(conn)
            if self.token is not None:
                if not server_handshake(conn, self.token):
                    return
            kind = msg["kind"]
            if kind == "ping":
                send_frame(conn, {"ok": True})
    '''
    fs = _findings(tmp_path, reordered, CLIENT_OK)
    assert any(f.rule == "PROTO205" and f.key == "unpickle-before-auth"
               for f in fs)
    # no handshake anywhere in the class
    no_auth = '''
    def recv_frame(conn): ...
    def send_frame(conn, obj): ...

    class Service:
        def _serve(self, conn):
            msg = recv_frame(conn)
            kind = msg["kind"]
            if kind == "ping":
                send_frame(conn, {"ok": True})
    '''
    fs = _findings(tmp_path, no_auth, CLIENT_OK)
    assert any(f.rule == "PROTO205" and f.key == "no-auth" for f in fs)


def test_proto206_mutating_kind_missing_seq_clock(tmp_path):
    bad_c = CLIENT_OK.replace(
        '''self._rpc({"kind": "put", "worker": self.w, "clock": self.c,
                   "seq": self.c, "delta": delta})''',
        'self._rpc({"kind": "put", "worker": self.w, "delta": delta})')
    fs = _findings(tmp_path, DISPATCHER_OK, bad_c)
    rules = _rules(fs)
    # the handler's required msg["clock"] read (the seq default) makes
    # this a PROTO203 too; the seq/clock dedup hole is the PROTO206
    assert "PROTO206" in rules
    assert any(f.key == "put.clock" and f.rule == "PROTO206" for f in fs)


def test_proto206_idempotent_membership_kind_needs_no_seq(tmp_path):
    # set.add / discard membership changes are idempotent: replaying
    # them is harmless, so a seq-less sender is fine
    d = DISPATCHER_OK + '''\
            elif kind == "leave":
                self.members.discard(msg["worker"])
                send_frame(conn, {"ok": True})
'''
    c = CLIENT_OK + '''
    def leave(self):
        self._rpc({"kind": "leave", "worker": self.w})
'''
    assert _findings(tmp_path, d, c) == []


FRAMING_OK = '''
import struct

def recv_exact(sock, n): ...
def max_frame(): ...

def recv(sock):
    (n,) = struct.unpack("!Q", recv_exact(sock, 8))
    cap = max_frame()
    if n > cap:
        raise ValueError(n)
    return recv_exact(sock, n)
'''


def test_proto207_unchecked_and_absurd_caps(tmp_path):
    f = tmp_path / "framing.py"
    # no bounds check at all
    f.write_text(textwrap.dedent(FRAMING_OK.replace(
        "    cap = max_frame()\n    if n > cap:\n        raise ValueError(n)\n",
        "")))
    fs = P.lint_framing(str(f))
    assert [x.key for x in fs] == ["unchecked-length"]
    # literal cap >= 2**31 is still absurd
    f.write_text(textwrap.dedent(FRAMING_OK.replace(
        "cap = max_frame()", "cap = 1 << 32")))
    fs = P.lint_framing(str(f))
    assert [x.key for x in fs] == ["absurd-cap"]
    # configurable cap: quiet (the shipped wire.py shape)
    f.write_text(textwrap.dedent(FRAMING_OK))
    assert P.lint_framing(str(f)) == []


def test_pragma_suppresses_proto_findings(tmp_path):
    bad = CLIENT_OK + '''
    def stats(self):
        return self._rpc({"kind": "stats"})  # static-ok: PROTO201
'''
    assert _findings(tmp_path, DISPATCHER_OK, bad) == []


# --------------------------------------------------------------------------- #
# the shipped tree
# --------------------------------------------------------------------------- #

def test_shipped_tree_has_zero_unbaselined_proto_findings():
    """The acceptance gate: every PROTO finding on the shipped tree is
    either fixed or baselined with a written reason."""
    new = filter_new(P.run_protocol_lint(), load_baseline())
    assert not new, [f.render() for f in new]


def test_shipped_schema_matches_checked_in_golden():
    """evidence/protocol_schema.json is the reviewed vocabulary; the
    extraction must reproduce it exactly (the CI --protocols gate)."""
    golden = P.load_schema()
    assert golden is not None, "run --refresh-schema and commit it"
    fresh, _ = P.extract_schema()
    assert P.diff_schema(golden, fresh) == []


def test_shipped_schema_content_highlights():
    """Headline vocabulary pins, from the GOLDEN (like the HLO contract
    headline test): the async tier's push is the only non-idempotent
    kind and carries seq+clock; every dispatcher kind has a sender."""
    golden = P.load_schema()
    ps = golden["services"]["param_service"]
    assert set(ps["kinds"]) == {"hello", "push", "heartbeat", "pull",
                                "admit", "retire", "clocks", "done", "bye",
                                "wire"}
    wire = ps["kinds"]["wire"]
    assert wire["mutating"] is False                # negotiation only
    assert "codec" in wire["reply_keys"]
    assert ps["unhandled_kinds"] == []
    push = ps["kinds"]["push"]
    assert push["mutating"] is True
    assert push["required_fields"] == ["clock", "delta", "worker"]
    assert set(push["sender_fields"]) >= {"clock", "seq", "delta",
                                          "worker", "full"}
    assert [k for k, v in ps["kinds"].items() if v["mutating"]] == ["push"]
    inf = golden["services"]["inference"]
    assert set(inf["kinds"]) == {"infer", "generate", "stats", "health",
                                 "reload", "bye", "wire"}
    assert inf["unhandled_kinds"] == []
    assert "outputs" in inf["kinds"]["infer"]["reply_keys"]
    gen = inf["kinds"]["generate"]
    assert gen["mutating"] is False
    assert gen["required_fields"] == ["inputs"]
    assert "stream" in gen["optional_fields"]      # gen_chunk streaming opt-in
    assert "tokens" in gen["reply_keys"]


def test_schema_diff_detects_vocabulary_drift():
    golden = P.load_schema()
    doctored = json.loads(json.dumps(golden))
    del doctored["services"]["param_service"]["kinds"]["retire"]
    diffs = P.diff_schema(doctored, golden)
    assert diffs and any("retire" in d for d in diffs)


# --------------------------------------------------------------------------- #
# collective-schedule consistency gate (pure pieces; the real lowering
# is exercised by the CI --collectives step and the contract goldens)
# --------------------------------------------------------------------------- #

_STABLEHLO_SNIPPET = '''
  %1 = "stablehlo.all_reduce"(%0) <{channel_handle =
       #stablehlo.channel_handle<handle = 7, type = 1>, replica_groups =
       dense<[[0, 1], [2, 3]]> : tensor<2x2xi64>}> ({
  %2 = "stablehlo.reduce_scatter"(%1) <{channel_handle =
       #stablehlo.channel_handle<handle = 9, type = 1>, replica_groups =
       dense<[[0, 2], [1, 3]]> : tensor<2x2xi64>, scatter_dimension =
       0 : i64}> ({
'''


def test_collective_sequence_normalizes_channels():
    from poseidon_tpu.analysis import contracts as C
    seq = C.collective_sequence(_STABLEHLO_SNIPPET)
    # channel ids renumbered by first appearance (7 -> c0, 9 -> c1), so
    # two participants whose process-global channel counters differ
    # still compare equal iff their schedules really match
    assert seq == [
        "all_reduce|[[0,1],[2,3]]||c0",
        "reduce_scatter|[[0,2],[1,3]]|scatter_dimension=0|c1",
    ]
    shifted = _STABLEHLO_SNIPPET.replace("handle = 7", "handle = 41") \
                                .replace("handle = 9", "handle = 43")
    assert C.collective_sequence(shifted) == seq


def test_collective_consistency_detects_divergence(monkeypatch):
    from poseidon_tpu.analysis import contracts as C
    texts = iter([_STABLEHLO_SNIPPET,
                  _STABLEHLO_SNIPPET.replace("[[0, 2], [1, 3]]",
                                             "[[0, 1], [2, 3]]")])
    monkeypatch.setattr(
        C, "_lower_mesh_participant",
        lambda model: (next(texts), None, None, None, None, None))
    ok, rep = C.collective_consistency(("lenet",), participants=2)
    assert not ok
    assert rep["lenet"]["diffs"] and \
        "diverges at collective #1" in rep["lenet"]["diffs"][0]


def test_collective_consistency_refuses_degenerate_extraction(monkeypatch):
    """If an MLIR printing change moves replica_groups out of the scanned
    attribute slice, the gate must REFUSE (infra error -> CLI exit 4),
    never vacuously pass two 'op|?|' sequences as equal."""
    from poseidon_tpu.analysis import contracts as C
    degenerate = '%1 = "stablehlo.all_reduce"(%0) ({\n'
    monkeypatch.setattr(
        C, "_lower_mesh_participant",
        lambda model: (degenerate, None, None, None, None, None))
    with pytest.raises(RuntimeError, match="degenerated"):
        C.collective_consistency(("lenet",), participants=2)


# --------------------------------------------------------------------------- #
# model checker
# --------------------------------------------------------------------------- #

def test_model_check_tiny_pinned():
    """Exact explored-state pin: the reachable state space is a
    deterministic function of the model — an edit that changes it must
    re-justify the number here."""
    res = M.explore(M.tiny_config())
    assert res.ok, [v for v in res.violations]
    assert (res.states, res.transitions) == (121, 230)


def test_model_check_smoke_acceptance_set():
    """The ISSUE 15 acceptance (all 2-worker x staleness {0,1,2} configs
    with one admit AND one retire event plus a crash/rejoin and a
    lost-ack replay in the schedule) extended by the ISSUE 16 fabric
    configs (a worker is a SLICE: slice-granular admit/retire, and
    leader failover crossed with lost acks and partial pushes) — all
    verify clean, with explored-state counts reported, well under the
    60 s CI budget."""
    t0 = time.time()
    results, caught = M.run_level("smoke")
    wall = time.time() - t0
    assert wall < 60.0, f"smoke level took {wall:.1f}s"
    by_name = {r.config.name: r for r in results}
    assert set(by_name) == {"2w-s0-admit-retire-crash",
                            "2w-s1-admit-retire-crash",
                            "2w-s2-admit-retire-crash",
                            "2slice-s1-admit-retire",
                            "2slice-s1-leader-failover"}
    for r in results:
        assert r.ok, (r.config.name, r.violations)
    for name in ("2w-s0-admit-retire-crash", "2w-s1-admit-retire-crash",
                 "2w-s2-admit-retire-crash", "2slice-s1-admit-retire"):
        assert by_name[name].config.admit_id is not None
        assert by_name[name].config.retire_worker is not None
    # exact state-space pins (regression detectors for silent pruning).
    # The pre-fabric counts are UNCHANGED: the new worker field (lost)
    # and budget element (failovers_left) are constant when
    # max_failovers == 0, so the old configs' reachable spaces are
    # isomorphic to their PR 15 shapes.
    assert by_name["2w-s0-admit-retire-crash"].states == 1354
    assert by_name["2w-s1-admit-retire-crash"].states == 7596
    assert by_name["2w-s2-admit-retire-crash"].states == 22622
    assert by_name["2slice-s1-admit-retire"].states == 1524
    assert by_name["2slice-s1-leader-failover"].states == 1336
    assert all(caught.values()), caught


def test_seeded_gate_on_raw_mutation_is_caught():
    """THE acceptance mutation: gating on raw clocks instead of durable
    clocks (the exact bug PR 12's durable vector exists to prevent) must
    produce a gate_safety violation with a concrete trace."""
    res = M.explore(M.smoke_configs()[1], mutation="gate_on_raw")
    assert not res.ok
    v = res.violations[0]
    assert v.invariant == "gate_safety"
    assert v.trace and any("push_partial" in step for step in v.trace)


def test_seeded_no_boundary_flush_breaks_the_sandwich():
    res = M.explore(M.smoke_configs()[1], mutation="no_boundary_flush")
    assert not res.ok
    assert res.violations[0].invariant == "durable_sandwich"


def test_seeded_replay_reapply_breaks_exactly_once():
    res = M.explore(M.smoke_configs()[1], mutation="replay_reapplies")
    assert not res.ok
    assert res.violations[0].invariant == "exactly_once"


def test_seeded_retire_stays_member_deadlocks():
    """A retired slot that stays in the gate denominator wedges the
    survivors — the deadlock detector must find it and name the trace."""
    caught = M.selftest_mutations()
    assert caught["retire_stays_member"]
    cfg = M.Config(name="dl", n_workers=2, staleness=1, n_clocks=4,
                   retire_worker=1, retire_after=0)
    res = M.explore(cfg, mutation="retire_stays_member")
    assert not res.ok
    assert res.violations[0].invariant == "deadlock"


def test_seeded_failover_loses_residual_is_caught():
    """ISSUE 16 acceptance mutation #1: a failover successor that drops
    the slice's parked residual must trip the completeness monitor at
    the next full flush — the bytes a partial push deferred are SLICE
    state, and exactly what the ledger replication exists to carry."""
    cfg = M.Config(name="fo-resid", n_workers=2, staleness=1, n_clocks=3,
                   managed=True, max_failovers=1)
    res = M.explore(cfg, mutation="leader_failover_loses_residual")
    assert not res.ok
    v = res.violations[0]
    assert v.invariant == "failover_completeness"
    # the trace must really be partial-push -> failover -> full flush
    assert any("push_partial" in step for step in v.trace)
    assert any("failover" in step for step in v.trace)
    # the correct protocol under the same schedule verifies clean
    assert M.explore(cfg).ok


def test_seeded_double_apply_across_leaders_is_caught():
    """ISSUE 16 acceptance mutation #2: a successor that restarts its
    seq stream instead of re-deriving the high-water mark re-applies the
    ledgered entry whose ack died with the old leader — the
    exactly-once monitor must flag it."""
    cfg = M.Config(name="fo-dup", n_workers=2, staleness=1, n_clocks=3,
                   managed=True, max_lost_acks=1, max_failovers=1)
    res = M.explore(cfg, mutation="double_apply_across_leaders")
    assert not res.ok
    v = res.violations[0]
    assert v.invariant == "exactly_once"
    assert any("push_full_acklost" in step for step in v.trace)
    assert v.trace[-1].startswith("failover")
    assert M.explore(cfg).ok


def test_failover_family_off_by_default_preserves_state_space():
    """max_failovers=0 must leave the pre-fabric model bit-identical:
    same states, same transitions (the pins above depend on it)."""
    res = M.explore(M.tiny_config())
    assert (res.states, res.transitions) == (121, 230)
    # and enabling the family strictly grows the explored space
    grown = M.explore(M.Config(name="tiny-fo", n_workers=2, staleness=1,
                               n_clocks=3, managed=True, max_failovers=1))
    assert grown.ok
    assert grown.states > res.states


def test_unknown_mutation_rejected():
    with pytest.raises(ValueError, match="unknown mutation"):
        M.explore(M.tiny_config(), mutation="bogus")


def test_dense_mode_has_no_partial_states():
    """managed=False (no budget) must reduce to the dense protocol:
    durable == raw everywhere, strictly fewer states."""
    cfg = M.Config(name="dense", n_workers=2, staleness=1, n_clocks=3,
                   managed=False)
    res = M.explore(cfg)
    assert res.ok
    managed = M.explore(M.tiny_config())
    assert res.states < managed.states


# --------------------------------------------------------------------------- #
# trace conformance: the model vs the real tier
# --------------------------------------------------------------------------- #

def _zeros(n=65536):
    return {"l": {"w": np.zeros(n, np.float32)}}


def _mk_step(w, n=65536):
    def step(cache, i):
        d = (np.arange(n) % (w + 2)).astype(np.float32) * 2.0 ** -12
        new = {l: {p: cache[l][p] + d for p in cache[l]} for l in cache}
        return new, 0.5
    return step


@pytest.mark.serving
def test_trace_conformance_real_two_worker_run():
    """The harness that keeps the model honest: a REAL 2-worker managed
    run (tight budget -> partial pushes), plus an elastic admission and
    a retirement, recorded by the service and replayed through the
    model's service rules; every client's passed gates must satisfy the
    durable-staleness bound they were admitted under."""
    from poseidon_tpu.parallel.async_ssp import (ParamService,
                                                 run_async_ssp_worker)
    staleness = 1
    svc = ParamService(_zeros(), n_workers=2, record_events=True)
    clients = {"budget_mbps": 0.02, "priority_frac": 0.25,
               "record_events": True}
    results = {}
    threads = []

    def run(w, **kw):
        results[w] = run_async_ssp_worker(
            w, 2, _zeros(), _mk_step(w), n_clocks=4, staleness=staleness,
            service=svc, client_opts=dict(clients), **kw)

    for w in range(2):
        kw = {"retire_at_clock": 2} if w == 1 else {}
        t = threading.Thread(target=run, args=(w,), kwargs=kw)
        t.start()
        threads.append(t)
    time.sleep(0.3)
    tj = threading.Thread(target=run, args=(2,), kwargs={"join": True})
    tj.start()
    threads.append(tj)
    for t in threads:
        t.join(timeout=60)
    svc.close()

    events = list(svc.events)
    counts = M.conform_service_events(events, staleness=staleness,
                                      n_workers=2)
    assert counts["push"] > 0
    assert counts["admit"] == 1
    assert counts["retire"] == 1
    # the tight budget really exercised the partial path somewhere
    assert any(e[0] == "push" and not e[3] for e in events), \
        "no partial push was recorded — the budget was not tight enough"
    # (worker, clock) applied exactly once across the whole run
    applied = [(e[1], e[2]) for e in events
               if e[0] == "push" and not e[4]]
    assert len(applied) == len(set(applied))


@pytest.mark.serving
def test_trace_conformance_gate_events():
    from poseidon_tpu.parallel.async_ssp import (ParamService,
                                                 run_async_ssp_worker)
    svc = ParamService(_zeros(1024), n_workers=2, record_events=True)
    results = {}
    threads = []

    def run(w):
        results[w] = run_async_ssp_worker(
            w, 2, _zeros(1024), _mk_step(w, 1024), n_clocks=3, staleness=0,
            service=svc,
            client_opts={"record_events": True})

    for w in range(2):
        t = threading.Thread(target=run, args=(w,))
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=60)
    svc.close()
    M.conform_service_events(svc.events, staleness=0, n_workers=2)
    gates = 0
    for w in range(2):
        gates += M.conform_gate_events(results[w]["events"],
                                       staleness=0)["gate"]
    assert gates >= 4    # both workers passed real gates, all safely


def test_conformance_rejects_doctored_traces():
    ev_dup = [("push", 0, 0, True, False), ("push", 0, 0, True, False)]
    with pytest.raises(M.TraceConformanceError, match="dedup diverged"):
        M.conform_service_events(ev_dup, staleness=1, n_workers=1)
    # boundary clock shipped partial: the force-flush contract broke
    ev_partial = [("push", 0, 0, True, False), ("push", 0, 1, False,
                                                False)]
    with pytest.raises(M.TraceConformanceError, match="force-flush"):
        M.conform_service_events(ev_partial, staleness=1, n_workers=1)
    # a gate that passed against a too-stale durable view
    with pytest.raises(M.TraceConformanceError, match="staleness bound"):
        M.conform_gate_events([("gate", 0, 5, 1)], staleness=1)
    assert M.conform_gate_events([("gate", 0, 5, 3)],
                                 staleness=1) == {"gate": 1}


# --------------------------------------------------------------------------- #
# CLI exit codes (subprocess-pinned, like tests/test_analysis.py)
# --------------------------------------------------------------------------- #

def _cli(*argv, timeout=180):
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "poseidon_tpu.analysis", *argv],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=timeout)


def test_cli_protocols_clean_on_shipped_tree():
    r = _cli("--protocols")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "protocol schema: ok" in r.stdout


def test_cli_protocols_schema_regression_exits_2(tmp_path):
    """Exit code 2 — reserved since PR 8 for contract violations — now
    fired by a protocol-schema regression."""
    golden = P.load_schema()
    doctored = json.loads(json.dumps(golden))
    doctored["services"]["param_service"]["kinds"]["push"][
        "required_fields"].remove("clock")
    path = tmp_path / "schema.json"
    path.write_text(json.dumps(doctored))
    r = _cli("--protocols", "--schema", str(path))
    assert r.returncode == 2, r.stdout + r.stderr
    assert "schema drift" in r.stdout


def test_cli_refresh_schema_roundtrip(tmp_path):
    path = tmp_path / "schema.json"
    r = _cli("--refresh-schema", "--schema", str(path))
    assert r.returncode == 0, r.stdout + r.stderr
    assert path.exists()
    r = _cli("--protocols", "--schema", str(path))
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_model_check_tiny_reports_states():
    r = _cli("--model-check", "tiny")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "121 states" in r.stdout
    assert "mutation self-test gate_on_raw: caught" in r.stdout


def test_cli_model_check_bad_level_exits_3():
    r = _cli("--model-check", "bogus")
    assert r.returncode == 3, r.stdout + r.stderr


def test_cli_protocols_with_explicit_paths_still_runs_proto_lint():
    """--protocols restricted to explicit lint paths must still run the
    cross-file protocol lint (an invocation that asked for the protocol
    gate must never read as a passed check that never ran)."""
    r = _cli("--protocols", "poseidon_tpu/proto/wire.py")
    assert r.returncode == 0, r.stdout + r.stderr
    assert "protocol schema: ok" in r.stdout
    # the baselined PROTO205 finding is counted (baselined, not new)
    assert "1 baselined" in r.stdout or "baselined" in r.stdout
