
import numpy as np

from poseidon_tpu.data.lmdb_reader import LMDBReader, LMDBWriter
from poseidon_tpu.data.sources import ImageListSource, SyntheticSource
from poseidon_tpu.data.transformer import DataTransformer
from poseidon_tpu.data.workload import Shard, contiguous_range, shard_indices
from poseidon_tpu.proto.messages import TransformationParameter
from poseidon_tpu.proto.wire import (Datum, decode_datum, encode_blob,
                                     decode_blob, encode_datum)


def test_datum_wire_roundtrip():
    arr = np.arange(2 * 3 * 4, dtype=np.uint8).reshape(2, 3, 4)
    d = Datum(channels=2, height=3, width=4, data=arr.tobytes(), label=7)
    d2 = decode_datum(encode_datum(d))
    assert d2.label == 7
    np.testing.assert_array_equal(d2.to_array(),
                                  arr.astype(np.float32))
    # float_data variant
    f = Datum(channels=3, height=1, width=1,
              float_data=np.asarray([1.5, -2.0, 0.25], np.float32))
    f2 = decode_datum(encode_datum(f))
    np.testing.assert_allclose(f2.to_array().ravel(), [1.5, -2.0, 0.25])


def test_blob_wire_roundtrip():
    arr = np.random.RandomState(0).randn(2, 3, 4, 5).astype(np.float32)
    b = decode_blob(encode_blob(arr))
    assert b.shape == (2, 3, 4, 5)
    np.testing.assert_allclose(b.to_array(), arr)


def test_lmdb_write_read_roundtrip(tmp_path):
    path = str(tmp_path / "db")
    w = LMDBWriter(path)
    values = {}
    rs = np.random.RandomState(0)
    for i in range(300):  # enough entries to force multiple leaves + branch
        key = f"{i:08d}".encode()
        val = rs.bytes(rs.randint(10, 200))
        values[key] = val
        w.put(key, val)
    # one oversized value to exercise overflow pages
    big_key = b"zz_big"
    big_val = rs.bytes(20000)
    values[big_key] = big_val
    w.put(big_key, big_val)
    w.close()

    r = LMDBReader(path)
    assert len(r) == 301
    seen = dict(iter(r))
    assert seen == values
    # keys come back sorted
    assert list(seen) == sorted(values)
    # random access
    assert r.value_at(0) == values[sorted(values)[0]]
    r.close()


def test_lmdb_datum_pipeline(tmp_path):
    path = str(tmp_path / "datumdb")
    w = LMDBWriter(path)
    rs = np.random.RandomState(1)
    for i in range(20):
        arr = rs.randint(0, 255, size=(3, 8, 8)).astype(np.uint8)
        d = Datum(3, 8, 8, arr.tobytes(), label=i % 10)
        w.put(f"{i:08d}".encode(), encode_datum(d))
    w.close()

    from poseidon_tpu.data.sources import LMDBSource
    src = LMDBSource(path)
    assert len(src) == 20
    arr, label = src.read(3)
    assert arr.shape == (3, 8, 8)
    assert label == 3


def test_transformer_center_crop_and_mean_values():
    tp = TransformationParameter(crop_size=2, mean_value=[1.0, 2.0, 3.0],
                                 scale=0.5)
    t = DataTransformer(tp, "TEST")
    x = np.arange(3 * 4 * 4, dtype=np.float32).reshape(1, 3, 4, 4)
    y = t(x)
    assert y.shape == (1, 3, 2, 2)
    # center crop offset (4-2)//2 = 1
    want = (x[0, :, 1:3, 1:3]
            - np.asarray([1, 2, 3], np.float32)[:, None, None]) * 0.5
    np.testing.assert_allclose(y[0], want)


def test_transformer_mean_file_indexed_at_crop(tmp_path):
    mean = np.random.RandomState(0).rand(1, 3, 4, 4).astype(np.float32)
    mean_path = str(tmp_path / "mean.binaryproto")
    with open(mean_path, "wb") as f:
        f.write(encode_blob(mean))
    tp = TransformationParameter(crop_size=2, mean_file=mean_path)
    t = DataTransformer(tp, "TEST")
    x = np.ones((1, 3, 4, 4), np.float32) * 10
    y = t(x)
    want = 10 - mean[0][:, 1:3, 1:3]
    np.testing.assert_allclose(y[0], want, rtol=1e-6)


def test_transformer_random_crop_mirror_train():
    tp = TransformationParameter(crop_size=3, mirror=True)
    t = DataTransformer(tp, "TRAIN", seed=0)
    x = np.random.RandomState(0).rand(64, 1, 5, 5).astype(np.float32)
    y = t(x)
    assert y.shape == (64, 1, 3, 3)
    # every output window must be an actual (possibly mirrored) crop
    found = 0
    for i in range(8):
        ok = False
        for ho in range(3):
            for wo in range(3):
                win = x[i, 0, ho:ho + 3, wo:wo + 3]
                if np.allclose(y[i, 0], win) or \
                        np.allclose(y[i, 0], win[:, ::-1]):
                    ok = True
        found += ok
    assert found == 8


def test_workload_sharding():
    n = 103
    ranges = [contiguous_range(n, Shard(i, 8)) for i in range(8)]
    assert ranges[0][0] == 0 and ranges[-1][1] == n
    sizes = [e - b for b, e in ranges]
    assert sum(sizes) == n and max(sizes) - min(sizes) <= 1
    # epoch permutation keeps shards disjoint and covering
    all_idx = np.concatenate(
        [shard_indices(n, Shard(i, 8), epoch=4) for i in range(8)])
    assert sorted(all_idx.tolist()) == list(range(n))


def test_batch_pipeline_memory_source():
    from poseidon_tpu.data.pipeline import BatchPipeline
    from poseidon_tpu.proto.messages import (LayerParameter,
                                             MemoryDataParameter)
    rs = np.random.RandomState(0)
    data = rs.rand(50, 1, 6, 6).astype(np.float32)
    labels = np.arange(50) % 3
    lp = LayerParameter(
        name="mem", type="MEMORY_DATA", top=["data", "label"],
        memory_data_param=MemoryDataParameter(batch_size=10, channels=1,
                                              height=6, width=6))
    pipe = BatchPipeline(lp, "TRAIN", 10,
                         memory_data={"data": data, "label": labels})
    batches = [next(pipe) for _ in range(5)]  # exactly one epoch of 50
    assert batches[0]["data"].shape == (10, 1, 6, 6)
    assert batches[0]["label"].shape == (10,)
    # one epoch covers every record exactly once, shuffled
    epoch_labels = np.concatenate([b["label"] for b in batches])
    assert sorted(epoch_labels.tolist()) == sorted(labels.tolist())
    assert not np.array_equal(epoch_labels, labels)  # shuffle happened
    pipe.close()


def test_image_list_source(tmp_path):
    from PIL import Image
    rs = np.random.RandomState(0)
    listfile = tmp_path / "list.txt"
    lines = []
    for i in range(4):
        img = Image.fromarray(
            rs.randint(0, 255, size=(10, 12, 3)).astype(np.uint8))
        p = tmp_path / f"img{i}.png"
        img.save(p)
        lines.append(f"{p} {i}")
    listfile.write_text("\n".join(lines))
    src = ImageListSource(str(listfile), new_height=8, new_width=8)
    assert len(src) == 4
    arr, label = src.read(2)
    assert arr.shape == (3, 8, 8)
    assert label == 2


def test_synthetic_source_learnable():
    src = SyntheticSource((1, 4, 4), num_classes=3, size=30)
    a0, l0 = src.read(0)
    a3, l3 = src.read(3)
    assert l0 == 0 and l3 == 0
    assert a0.shape == (1, 4, 4)
    # same class, different noise
    assert not np.allclose(a0, a3)


def test_window_data_source(tmp_path):
    from PIL import Image
    from poseidon_tpu.data.window import WindowDataSource
    from poseidon_tpu.proto.messages import (LayerParameter,
                                             TransformationParameter,
                                             WindowDataParameter)
    rs = np.random.RandomState(0)
    img_paths = []
    for i in range(2):
        img = Image.fromarray(rs.randint(0, 255, (40, 40, 3)).astype(np.uint8))
        p = tmp_path / f"w{i}.png"
        img.save(p)
        img_paths.append(str(p))
    wf = tmp_path / "windows.txt"
    wf.write_text(f"""# 0
{img_paths[0]}
3 40 40
3
1 0.9 5 5 20 20
2 0.7 10 10 30 30
0 0.1 0 0 10 10
# 1
{img_paths[1]}
3 40 40
2
1 0.8 0 0 15 15
0 0.05 20 20 39 39
""")
    lp = LayerParameter(
        name="wd", type="WINDOW_DATA", top=["data", "label"],
        window_data_param=WindowDataParameter(
            source=str(wf), batch_size=8, crop_size=12, fg_threshold=0.5,
            bg_threshold=0.3, fg_fraction=0.5, context_pad=2),
        transform_param=TransformationParameter(crop_size=12))
    src = WindowDataSource(lp, "TRAIN")
    assert len(src.fg) == 3 and len(src.bg) == 2
    data, labels = src.batch(8)
    assert data.shape == (8, 3, 12, 12)
    assert set(labels[:4]) <= {1, 2}   # fg half
    assert set(labels[4:]) == {0}      # bg half

    from poseidon_tpu.data.pipeline import BatchPipeline
    pipe = BatchPipeline(lp, "TRAIN", 8)
    b = next(pipe)
    assert b["data"].shape == (8, 3, 12, 12)
    pipe.close()


def test_libsvm_parser(tmp_path):
    from poseidon_tpu.data.libsvm import read_libsvm
    f = tmp_path / "data.svm"
    f.write_text("""1 1:0.5 3:1.5
-1 2:2.0 # comment
1 1:1.0 4:0.25
""")
    feats, labels = read_libsvm(str(f))
    np.testing.assert_allclose(labels, [1, -1, 1])
    dense = feats.to_dense()
    assert dense.shape == (3, 4)
    np.testing.assert_allclose(dense[0], [0.5, 0, 1.5, 0])
    np.testing.assert_allclose(dense[1], [0, 2.0, 0, 0])
