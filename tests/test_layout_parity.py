"""Layout parity: the NHWC plan is a performance policy, never a numerics one.

Acceptance criteria of the round-6 layout PR: with ``conv_layout="NHWC"``,
every spatial layer op and one full optimizer step of AlexNet and
GoogLeNet match the NCHW path on CPU within float tolerance (params and
grads compared in CANONICAL NCHW), and snapshots written under either
layout load under the other. Everything here runs mesh-free (plain jit /
grad) so the CPU tier stays independent of shard_map availability.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from poseidon_tpu.core.net import Net
from poseidon_tpu.models import zoo
from poseidon_tpu.proto.messages import (
    ConcatParameter, ConvolutionParameter, EltwiseParameter, LayerParameter,
    LRNParameter, MVNParameter, NetParameter, PoolingParameter,
    SliceParameter, SolverParameter)

jtu = jax.tree_util


def _tree_close(a, b, rtol=1e-5, atol=1e-6, msg=""):
    la, lb = jtu.tree_leaves(a), jtu.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=rtol, atol=atol, err_msg=msg)


def _both_layouts(net_param, shapes, inputs, train=True, rng_seed=7):
    """(outputs, param grads) under each layout, same canonical params."""
    rng = jax.random.PRNGKey(rng_seed)
    results = {}
    params = None
    for layout in ("NCHW", "NHWC"):
        net = Net(net_param, "TRAIN" if train else "TEST", shapes,
                  conv_layout=layout)
        if params is None:
            params = net.init(jax.random.PRNGKey(0))

        def loss_fn(p):
            out = net.apply(p, inputs, train=train, rng=rng)
            return out.loss, out.outputs

        if params:
            (loss, outs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
        else:
            loss, outs = loss_fn(params)
            grads = {}
        results[layout] = (loss, outs, grads)
    return results


def _single_layer_net(layer_lp, shapes, loss_bottom=None, label_shape=None):
    """Wrap one layer in a net with a loss so grads flow; shapes name the
    external inputs."""
    layers = [layer_lp]
    if loss_bottom is not None:
        from poseidon_tpu.models.zoo import ip, softmax_loss
        layers += [ip("fc", loss_bottom, "fc", 5),
                   softmax_loss("loss", ["fc", "label"])]
    return NetParameter(name="t", layers=layers)


RS = np.random.RandomState(42)


def _img(shape):
    return jnp.asarray(RS.randn(*shape).astype(np.float32))


# --------------------------------------------------------------------------- #
# per-layer-type parity (each spatial/structural op through the planner)
# --------------------------------------------------------------------------- #

def _layer_case(name):
    """(extra layer stack, input shape) per layer type under test; every
    case is conv -> <layer> -> fc/loss so the op under test runs inside a
    genuinely NHWC-planned region with grads flowing through it."""
    C = ConvolutionParameter
    conv = LayerParameter(
        name="conv", type="CONVOLUTION", bottom=["data"], top=["conv"],
        convolution_param=C(num_output=8, kernel_size=3, pad=1,
                            weight_filler=zoo.xavier(),
                            bias_filler=zoo.constant(0.1)))
    if name == "conv_group":
        lp = LayerParameter(
            name="op", type="CONVOLUTION", bottom=["conv"], top=["op"],
            convolution_param=C(num_output=8, kernel_size=3, pad=1, group=2,
                                weight_filler=zoo.xavier(),
                                bias_filler=zoo.constant(0.0)))
    elif name == "pool_max":
        lp = LayerParameter(
            name="op", type="POOLING", bottom=["conv"], top=["op"],
            pooling_param=PoolingParameter(pool="MAX", kernel_size=3,
                                           stride=2, pad=1))
    elif name == "pool_ave":
        lp = LayerParameter(
            name="op", type="POOLING", bottom=["conv"], top=["op"],
            pooling_param=PoolingParameter(pool="AVE", kernel_size=3,
                                           stride=2, pad=1))
    elif name == "pool_global":
        lp = LayerParameter(
            name="op", type="POOLING", bottom=["conv"], top=["op"],
            pooling_param=PoolingParameter(pool="AVE", global_pooling=True))
    elif name == "lrn_across":
        lp = LayerParameter(
            name="op", type="LRN", bottom=["conv"], top=["op"],
            lrn_param=LRNParameter(local_size=5, alpha=1e-4, beta=0.75))
    elif name == "lrn_within":
        lp = LayerParameter(
            name="op", type="LRN", bottom=["conv"], top=["op"],
            lrn_param=LRNParameter(local_size=3, alpha=1e-4, beta=0.75,
                                   norm_region="WITHIN_CHANNEL"))
    elif name == "mvn":
        lp = LayerParameter(
            name="op", type="MVN", bottom=["conv"], top=["op"],
            mvn_param=MVNParameter(normalize_variance=True,
                                   across_channels=False))
    elif name == "eltwise":
        return None  # multi-bottom; built in its own test
    else:
        raise KeyError(name)
    return [conv, lp]


@pytest.mark.parametrize("case", [
    "conv_group", "pool_max", "pool_ave", "pool_global",
    "lrn_across", "lrn_within", "mvn",
])
def test_layer_type_parity(case):
    layers = _layer_case(case)
    from poseidon_tpu.models.zoo import ip, softmax_loss
    np_ = NetParameter(name="t", layers=layers + [
        ip("fc", "op", "fc", 5), softmax_loss("loss", ["fc", "label"])])
    shapes = {"data": (2, 4, 9, 9), "label": (2,)}
    inputs = {"data": _img((2, 4, 9, 9)),
              "label": jnp.asarray(RS.randint(0, 5, (2,)))}
    r = _both_layouts(np_, shapes, inputs)
    _tree_close(r["NCHW"][0], r["NHWC"][0], msg=f"{case}: loss")
    _tree_close(r["NCHW"][2], r["NHWC"][2], rtol=1e-4, atol=1e-5,
                msg=f"{case}: grads")


def test_concat_slice_eltwise_softmax_parity():
    """The structural seams the old shim stranded transposes across:
    slice on channels -> eltwise -> concat -> in-graph SOFTMAX on a 4-D
    blob, all inside the NHWC region."""
    from poseidon_tpu.models.zoo import conv as zconv, ip, softmax_loss
    layers = [
        zconv("conv", "data", "conv", 8, 3, pad=1),
        LayerParameter(name="sl", type="SLICE", bottom=["conv"],
                       top=["s1", "s2"],
                       slice_param=SliceParameter(slice_dim=1)),
        LayerParameter(name="ew", type="ELTWISE", bottom=["s1", "s2"],
                       top=["ew"],
                       eltwise_param=EltwiseParameter(operation="SUM",
                                                      coeff=[0.5, 2.0])),
        LayerParameter(name="cat", type="CONCAT", bottom=["ew", "s1"],
                       top=["cat"],
                       concat_param=ConcatParameter(concat_dim=1)),
        LayerParameter(name="sm", type="SOFTMAX", bottom=["cat"],
                       top=["sm"]),
        ip("fc", "sm", "fc", 5),
        softmax_loss("loss", ["fc", "label"]),
    ]
    np_ = NetParameter(name="t", layers=layers)
    shapes = {"data": (2, 4, 7, 7), "label": (2,)}
    inputs = {"data": _img((2, 4, 7, 7)),
              "label": jnp.asarray(RS.randint(0, 5, (2,)))}
    r = _both_layouts(np_, shapes, inputs)
    _tree_close(r["NCHW"][0], r["NHWC"][0], msg="loss")
    _tree_close(r["NCHW"][2], r["NHWC"][2], rtol=1e-4, atol=1e-5,
                msg="grads")


def test_dropout_rng_is_layout_portable():
    """Dropout masks must not depend on the physical layout (the layer is
    planned canonical precisely for this) — train-mode losses match
    BITWISE across plans for the same rng."""
    from poseidon_tpu.models.zoo import conv as zconv, dropout, ip, \
        softmax_loss
    layers = [
        zconv("conv", "data", "conv", 8, 3, pad=1),
        dropout("drop", "conv", 0.5),
        ip("fc", "conv", "fc", 5),
        softmax_loss("loss", ["fc", "label"]),
    ]
    np_ = NetParameter(name="t", layers=layers)
    shapes = {"data": (2, 4, 7, 7), "label": (2,)}
    inputs = {"data": _img((2, 4, 7, 7)),
              "label": jnp.asarray(RS.randint(0, 5, (2,)))}
    r = _both_layouts(np_, shapes, inputs, train=True)
    assert float(r["NCHW"][0]) == float(r["NHWC"][0])


# --------------------------------------------------------------------------- #
# full-net optimizer-step parity (the acceptance bar)
# --------------------------------------------------------------------------- #

def _one_step(net, params, batch, input_layout="NCHW"):
    from poseidon_tpu.parallel.trainer import param_mults
    from poseidon_tpu.solvers.updates import init_state, make_update_fn
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         weight_decay=5e-4)
    update = make_update_fn(sp, param_mults(net))

    @jax.jit
    def step(p, s, b):
        def loss_fn(pp):
            return net.apply(pp, b, train=True, rng=jax.random.PRNGKey(3),
                             input_layout=input_layout).loss
        g = jax.grad(loss_fn)(p)
        return update(p, g, s)

    return step(params, init_state(params), batch)


@pytest.mark.parametrize("model,image,batch", [
    ("alexnet", 67, 2),
    pytest.param("googlenet", 224, 1, marks=pytest.mark.slow),
])
def test_full_net_optimizer_step_parity(model, image, batch):
    """One full momentum+weight-decay optimizer step under each plan:
    updated params (canonical layout by construction) must agree within
    float tolerance. AlexNet runs at a reduced image size to keep the CPU
    tier fast; GoogLeNet (224 required by its pooling tree) is the slow-
    marked heavyweight variant."""
    np_ = getattr(zoo, model)(num_classes=10, with_accuracy=False)
    shapes = {"data": (batch, 3, image, image), "label": (batch,)}
    batch_arrs = {"data": _img(shapes["data"]),
                  "label": jnp.asarray(RS.randint(0, 10, (batch,)))}
    out = {}
    params = None
    for layout in ("NCHW", "NHWC"):
        net = Net(np_, "TRAIN", shapes, conv_layout=layout)
        if params is None:
            params = net.init(jax.random.PRNGKey(0))
        out[layout], _ = _one_step(net, params, batch_arrs)
    _tree_close(out["NCHW"], out["NHWC"], rtol=1e-4, atol=1e-6,
                msg=f"{model}: params after one step")


def test_nhwc_fed_input_matches_canonical_feed():
    """Feeding channels-last directly (the transpose-free hot path) is the
    same computation as feeding the Caffe NCHW contract."""
    np_ = zoo.alexnet(num_classes=10, with_accuracy=False)
    shapes = {"data": (2, 3, 67, 67), "label": (2,)}
    net = Net(np_, "TRAIN", shapes, conv_layout="NHWC")
    params = net.init(jax.random.PRNGKey(0))
    x = _img((2, 3, 67, 67))
    lbl = jnp.asarray(RS.randint(0, 10, (2,)))
    rng = jax.random.PRNGKey(5)
    l_nchw = net.apply(params, {"data": x, "label": lbl}, train=True,
                       rng=rng).loss
    l_nhwc = net.apply(params, {"data": jnp.transpose(x, (0, 2, 3, 1)),
                                "label": lbl}, train=True, rng=rng,
                       input_layout="NHWC").loss
    assert float(l_nchw) == float(l_nhwc)


def test_keep_blobs_and_outputs_are_canonical():
    """Blob export is a genuine boundary: every 4-D blob coming out of an
    NHWC-planned net is canonical NCHW with its logical shape."""
    np_ = zoo.alexnet(num_classes=10, with_accuracy=False)
    shapes = {"data": (2, 3, 67, 67), "label": (2,)}
    net = Net(np_, "TRAIN", shapes, conv_layout="NHWC")
    params = net.init(jax.random.PRNGKey(0))
    out = net.apply(params, {"data": _img((2, 3, 67, 67)),
                             "label": jnp.asarray([0, 1])},
                    train=False, keep_blobs=True)
    for name, blob in out.blobs.items():
        if getattr(blob, "ndim", 0) == 4:
            assert tuple(blob.shape) == net.blob_shapes[name], name


# --------------------------------------------------------------------------- #
# snapshots / weights are layout-portable
# --------------------------------------------------------------------------- #

def test_weights_roundtrip_across_layouts(tmp_path):
    """Params are canonical under either plan: weights exported by an
    NHWC-planned net load into an NCHW-planned net (and back) with
    identical forward results — snapshots never encode the layout."""
    np_ = zoo.alexnet(num_classes=10, with_accuracy=False)
    shapes = {"data": (2, 3, 67, 67), "label": (2,)}
    nets = {lay: Net(np_, "TRAIN", shapes, conv_layout=lay)
            for lay in ("NCHW", "NHWC")}
    params = nets["NHWC"].init(jax.random.PRNGKey(1))
    blobs = nets["NHWC"].export_weights(params)
    restored = nets["NCHW"].load_weights(nets["NCHW"].init(
        jax.random.PRNGKey(2)), blobs)
    _tree_close(params, restored)
    inputs = {"data": _img((2, 3, 67, 67)),
              "label": jnp.asarray(RS.randint(0, 10, (2,)))}
    rng = jax.random.PRNGKey(9)
    l1 = nets["NHWC"].apply(params, inputs, train=True, rng=rng).loss
    l2 = nets["NCHW"].apply(restored, inputs, train=True, rng=rng).loss
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)


def test_snapshot_roundtrip_across_layouts(tmp_path):
    """The runtime snapshot files written under one plan restore under the
    other (checkpoints stay NCHW-canonical)."""
    from poseidon_tpu.parallel.trainer import init_train_state
    from poseidon_tpu.runtime.checkpoint import restore, snapshot
    np_ = zoo.lenet(with_accuracy=False)
    shapes = {"data": (2, 1, 28, 28), "label": (2,)}
    net_a = Net(np_, "TRAIN", shapes, conv_layout="NHWC")
    params = net_a.init(jax.random.PRNGKey(0))
    state = init_train_state(params)
    _, state_path = snapshot(str(tmp_path / "snap"), net_a, params, state)
    loaded_params, _ = restore(state_path)
    net_b = Net(np_, "TRAIN", shapes, conv_layout="NCHW")
    inputs = {"data": _img((2, 1, 28, 28)),
              "label": jnp.asarray([1, 2])}
    l_a = net_a.apply(params, inputs, train=False).loss
    l_b = net_b.apply(jtu.tree_map(jnp.asarray, loaded_params), inputs,
                      train=False).loss
    np.testing.assert_allclose(float(l_a), float(l_b), rtol=1e-6)


# --------------------------------------------------------------------------- #
# fused conv epilogues
# --------------------------------------------------------------------------- #

def test_epilogue_fusion_is_exact_and_optional():
    """conv->in-place-relu folds into the conv epilogue; the fold is
    BITWISE identical to the unfused net (same formula), in both layouts."""
    np_ = zoo.alexnet(num_classes=10, with_accuracy=False)
    shapes = {"data": (2, 3, 67, 67), "label": (2,)}
    inputs = {"data": _img((2, 3, 67, 67)),
              "label": jnp.asarray(RS.randint(0, 10, (2,)))}
    rng = jax.random.PRNGKey(4)
    for layout in ("NCHW", "NHWC"):
        fused = Net(np_, "TRAIN", shapes, conv_layout=layout)
        plain = Net(np_, "TRAIN", shapes, conv_layout=layout,
                    fuse_conv_epilogues=False)
        assert any(l.fused_relu_slope is not None for l in fused.layers)
        assert all(l.fused_relu_slope is None for l in plain.layers
                   if l.TYPE == "CONVOLUTION")
        params = fused.init(jax.random.PRNGKey(0))
        lf = fused.apply(params, inputs, train=True, rng=rng).loss
        lp = plain.apply(params, inputs, train=True, rng=rng).loss
        assert float(lf) == float(lp), layout


def test_epilogue_fusion_skips_non_inplace_and_loss_weighted():
    """Guards: a ReLU writing a DIFFERENT top keeps the conv's own blob
    pre-activation (no fold); a loss_weight on the conv top reads the
    pre-activation sum (no fold)."""
    from poseidon_tpu.models.zoo import ip, softmax_loss
    C = ConvolutionParameter
    conv = LayerParameter(
        name="conv", type="CONVOLUTION", bottom=["data"], top=["conv"],
        convolution_param=C(num_output=4, kernel_size=3,
                            weight_filler=zoo.xavier(),
                            bias_filler=zoo.constant(0.0)))
    relu_out = LayerParameter(name="relu", type="RELU", bottom=["conv"],
                              top=["act"])
    np_ = NetParameter(name="t", layers=[
        conv, relu_out, ip("fc", "act", "fc", 3),
        softmax_loss("loss", ["fc", "label"])])
    net = Net(np_, "TRAIN", {"data": (2, 2, 7, 7), "label": (2,)})
    assert net._layer_by_name["conv"].fused_relu_slope is None

    conv_lw = LayerParameter(
        name="conv", type="CONVOLUTION", bottom=["data"], top=["conv"],
        loss_weight=[0.1],
        convolution_param=C(num_output=4, kernel_size=3,
                            weight_filler=zoo.xavier(),
                            bias_filler=zoo.constant(0.0)))
    relu_in = LayerParameter(name="relu", type="RELU", bottom=["conv"],
                             top=["conv"])
    np2 = NetParameter(name="t2", layers=[
        conv_lw, relu_in, ip("fc", "conv", "fc", 3),
        softmax_loss("loss", ["fc", "label"])])
    net2 = Net(np2, "TRAIN", {"data": (2, 2, 7, 7), "label": (2,)})
    assert net2._layer_by_name["conv"].fused_relu_slope is None


def test_conv_scale_shift_epilogue():
    """The BN-folded inference epilogue: y = (conv+b)*scale + shift, per
    output channel, fused into the conv call — same numbers as the
    explicit elementwise chain, both layouts."""
    from poseidon_tpu.ops import nn as NN
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(2, 3, 9, 9).astype(np.float32))
    w = jnp.asarray(rs.randn(6, 3, 3, 3).astype(np.float32))
    b = jnp.asarray(rs.randn(6).astype(np.float32))
    scale = jnp.asarray(rs.rand(6).astype(np.float32) + 0.5)
    shift = jnp.asarray(rs.randn(6).astype(np.float32))
    base = NN.conv2d(x, w, b, (1, 1), (1, 1))
    want = jnp.maximum(base * scale.reshape(1, -1, 1, 1)
                       + shift.reshape(1, -1, 1, 1), 0)
    got = NN.conv2d(x, w, b, (1, 1), (1, 1), scale=scale, shift=shift,
                    act="relu")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)
    xt = jnp.transpose(x, (0, 2, 3, 1))
    got_nhwc = NN.conv2d(xt, w, b, (1, 1), (1, 1), layout="NHWC",
                         scale=scale, shift=shift, act="relu")
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(got_nhwc, (0, 3, 1, 2))),
        np.asarray(want), rtol=1e-5, atol=1e-5)


def test_s2d_stem_rewrite_parity_nhwc():
    """The space-to-depth stem rewrite stays exact under the NHWC plan
    (its channel flattening order matches the canonical kernel rewrite)."""
    from poseidon_tpu import config
    from poseidon_tpu.ops import nn as NN
    rs = np.random.RandomState(11)
    x = jnp.asarray(rs.randn(2, 3, 19, 19).astype(np.float32))
    w = jnp.asarray(rs.randn(8, 3, 5, 5).astype(np.float32))
    b = jnp.asarray(rs.randn(8).astype(np.float32))
    ref = NN.conv2d(x, w, b, (2, 2), (1, 1))
    with config.policy_scope(conv_s2d=True):
        got_nchw = NN.conv2d(x, w, b, (2, 2), (1, 1))
        got_nhwc = NN.conv2d(jnp.transpose(x, (0, 2, 3, 1)), w, b,
                             (2, 2), (1, 1), layout="NHWC")
    np.testing.assert_allclose(np.asarray(got_nchw), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(jnp.transpose(got_nhwc, (0, 3, 1, 2))),
        np.asarray(ref), rtol=1e-4, atol=1e-5)


def test_conv_layout_auto_resolves_per_backend():
    """``conv_layout="auto"`` resolves at Net construction: NCHW on TPU
    (NHWC measured 0.53x on the real v5e in BENCH_r05 despite winning the
    HLO-transpose count), NHWC on GPU (tensor-core native), NCHW on CPU /
    unknown backends; explicit overrides pass through untouched."""
    from poseidon_tpu.numeric import resolve_conv_layout

    assert resolve_conv_layout("auto", backend="tpu") == "NCHW"
    assert resolve_conv_layout("auto", backend="gpu") == "NHWC"
    assert resolve_conv_layout("auto", backend="cpu") == "NCHW"
    assert resolve_conv_layout("auto", backend="something_else") == "NCHW"
    assert resolve_conv_layout("NHWC", backend="tpu") == "NHWC"
    assert resolve_conv_layout("nchw", backend="gpu") == "NCHW"

    # a Net built under "auto" lands on this backend's resolved layout
    # (the suite runs on CPU -> NCHW) and still trains/applies
    np_ = NetParameter(name="auto_net", layers=[
        LayerParameter(name="c", type="CONVOLUTION", bottom=["data"],
                       top=["c"],
                       convolution_param=ConvolutionParameter(
                           num_output=4, kernel_size=3)),
    ], input=["data"], input_dim=[2, 3, 8, 8])
    net = Net(np_, "TEST", conv_layout="auto")
    assert net.conv_layout == resolve_conv_layout("auto")
    assert net.conv_layout in ("NCHW", "NHWC")

    # the ambient policy accepts "auto" too
    from poseidon_tpu import config
    with config.policy_scope(conv_layout="auto"):
        net2 = Net(np_, "TEST")
        assert net2.conv_layout == resolve_conv_layout("auto")
