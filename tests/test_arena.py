"""Flat parameter arena (core/arena.py): bit-exactness and bucketed sync.

The arena packs DENSE f32 param/grad/momentum leaves into one flat buffer
with a static DWBP-ordered offset table, syncs gradients as
ceil(bytes/arena_bucket_mb) bucketed collectives, and runs the optimizer
update as one fused elementwise pass. Everything here pins the two arena
contracts:

- the arena step computes the per-leaf step's numbers on CPU: the fused
  update RULE is bit-identical (pinned at the op level), full LeNet steps
  are bit-identical end to end, and full AlexNet/GoogLeNet steps agree to
  <= 1 ulp (XLA may pick a different cross-replica reduction order for a
  bucketed all-reduce than for a tiny per-leaf psum) — for every solver
  rule, both numeric policies, wire dtypes, gradient accumulation, scan
  dispatch, and SSP; and
- the compiled data-parallel program carries at most
  ceil(total_grad_bytes / arena_bucket_mb) gradient all-reduces instead of
  one per leaf.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from poseidon_tpu import config
from poseidon_tpu.core.net import Net
from poseidon_tpu.models import zoo
from poseidon_tpu.parallel import (CommConfig, build_ssp_train_step,
                                   build_train_step, init_ssp_state,
                                   init_train_state, make_mesh)
from poseidon_tpu.proto.messages import SolverParameter
from poseidon_tpu.runtime.hlo_comm import count_gradient_all_reduces

N_DEV = 8
BATCH = 16


@pytest.fixture(scope="module")
def mesh():
    assert jax.device_count() == N_DEV
    return make_mesh()


@pytest.fixture(scope="module")
def lenet_net():
    return Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
               source_shapes=zoo.lenet_shapes(BATCH // N_DEV))


def _batch(rng):
    return {
        "data": jnp.asarray(rng.randn(BATCH, 1, 28, 28).astype(np.float32)),
        "label": jnp.asarray(rng.randint(0, 10, size=(BATCH,))),
    }


def _assert_tree_equal(a, b, msg=""):
    for l in a:
        for k in a[l]:
            np.testing.assert_array_equal(
                np.asarray(a[l][k]), np.asarray(b[l][k]),
                err_msg=f"{msg} {l}/{k}")
    assert set(a) == set(b)


def _ab_step(net, sp, mesh, comm, params, batch, rng, n_steps=1):
    """(arena result, per-leaf result) after n_steps from the same start."""
    import dataclasses
    out = []
    for arena_on in (True, False):
        cc = dataclasses.replace(comm, param_arena=arena_on)
        ts = build_train_step(net, sp, mesh, cc, donate=False)
        assert (ts.arena is not None) == arena_on
        p, s = params, init_train_state(params, cc, N_DEV)
        for i in range(n_steps):
            p, s, m = ts.step(p, s, batch, jax.random.fold_in(rng, i))
        out.append((p, s, m))
    return out


# --------------------------------------------------------------------------- #
# offset table / views unit behavior
# --------------------------------------------------------------------------- #

def test_offset_table_is_dwbp_ordered(lenet_net):
    """Slots run in REVERSE forward layer order (the order gradients
    materialize in backward), contiguously from offset 0."""
    layout = lenet_net.arena_layout()
    layer_order = [l.name for l in lenet_net.layers
                   if l.name in lenet_net.param_defs]
    seen = [s.layer for s in layout.slots]
    # first slot belongs to the LAST param layer
    assert seen[0] == layer_order[-1]
    assert seen[-1] == layer_order[0]
    off = 0
    for s in layout.slots:
        assert s.offset == off
        off += s.size
    assert layout.total == off == lenet_net.param_count()


def test_pack_unpack_roundtrip_and_views_grad(lenet_net):
    """unpack(pack(t)) == t bit-for-bit, and the views custom-vjp delivers
    the cotangent PACKED: grad of sum(leaf * const) wrt the bucket buffers
    equals the packed consts — including leaves that SPAN bucket
    boundaries (tiny bucket_mb forces spanning)."""
    layout = lenet_net.arena_layout(bucket_mb=0.037)  # ~9.2k elems/bucket
    assert layout.n_buckets == math.ceil(
        layout.total_bytes() / (0.037 * 1e6))
    params = lenet_net.init(jax.random.PRNGKey(0))
    flat = layout.pack(params)
    assert flat.shape == (layout.total,)
    _assert_tree_equal(layout.unpack(flat), params, "roundtrip")

    rs = np.random.RandomState(1)
    consts = jax.tree_util.tree_map(
        lambda v: jnp.asarray(rs.randn(*v.shape).astype(np.float32)), params)

    def f(*bufs):
        tree = layout.views(*bufs)
        return sum(jnp.vdot(tree[l][k], consts[l][k])
                   for l in tree for k in tree[l])

    grads = jax.grad(f, argnums=tuple(range(layout.n_buckets)))(
        *layout.split_buckets(flat))
    np.testing.assert_array_equal(
        np.asarray(layout.join_buckets(list(grads))),
        np.asarray(layout.pack(consts)))


def test_residual_merge_partition(lenet_net):
    layout = lenet_net.arena_layout(include=frozenset({"conv1", "ip2"}))
    params = lenet_net.init(jax.random.PRNGKey(0))
    excl = layout.residual(params)
    assert set(excl) == {"conv2", "ip1"}
    _assert_tree_equal(layout.merge(layout.unpack(layout.pack(params)),
                                    excl), params, "partition")


def test_non_f32_leaf_fails_loudly(lenet_net):
    layout = lenet_net.arena_layout()
    params = lenet_net.init(jax.random.PRNGKey(0))
    params["conv1"]["w"] = params["conv1"]["w"].astype(jnp.bfloat16)
    with pytest.raises(TypeError, match="f32-homogeneous"):
        layout.pack(params)


# --------------------------------------------------------------------------- #
# fused update rule == per-leaf rule, bit for bit
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("solver_type,reg", [
    ("SGD", "L2"), ("SGD", "L1"), ("NESTEROV", "L2"), ("ADAGRAD", "L2")])
def test_fused_update_matches_leafwise(lenet_net, solver_type, reg, rng_np):
    """make_fused_update_fn over the packed buffer == make_update_fn per
    leaf, including mixed lr/decay multipliers and the zero-decay skip."""
    from poseidon_tpu.parallel.trainer import param_mults
    from poseidon_tpu.solvers.updates import (SolverState, init_state,
                                              make_fused_update_fn,
                                              make_update_fn)
    sp = SolverParameter(base_lr=0.02, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005, solver_type=solver_type,
                         regularization_type=reg)
    layout = lenet_net.arena_layout()
    params = lenet_net.init(jax.random.PRNGKey(0))
    grads = jax.tree_util.tree_map(
        lambda v: jnp.asarray(rng_np.randn(*v.shape).astype(np.float32)),
        params)
    state = init_state(params)
    # two per-leaf steps (nonzero history exercises the momentum term)
    update = make_update_fn(sp, param_mults(lenet_net))
    p1, s1 = update(params, grads, state)
    p1, s1 = update(p1, grads, s1)

    from poseidon_tpu.solvers.updates import learning_rate
    fused = make_fused_update_fn(sp, layout)
    fw, fh = layout.pack(params), layout.pack(state.history)
    for it in range(2):
        rate = learning_rate(sp, jnp.asarray(it, jnp.int32))
        fw, fh = fused(fw, layout.pack(grads), fh, rate)
    _assert_tree_equal(layout.unpack(fw), p1, "params")
    _assert_tree_equal(layout.unpack(fh), s1.history, "history")


def test_pallas_fused_sgd_matches_xla(monkeypatch, rng_np):
    """The Pallas kernel variant (interpret mode off-TPU) computes the
    exact same update as the XLA formulation, odd lengths included."""
    from poseidon_tpu.ops.pallas_kernels import fused_sgd
    n = 4097  # not a lane multiple: exercises pad + slice-off
    w = jnp.asarray(rng_np.randn(n).astype(np.float32))
    g = jnp.asarray(rng_np.randn(n).astype(np.float32))
    h = jnp.asarray(rng_np.randn(n).astype(np.float32))
    lr = jnp.asarray(np.abs(rng_np.randn(n)).astype(np.float32))
    dec = jnp.asarray(
        (rng_np.rand(n) > 0.5).astype(np.float32) * np.float32(5e-4))
    w2, h2 = jax.jit(lambda *a: fused_sgd(*a, 0.9, interpret=True))(
        w, g, h, lr, dec)

    @jax.jit
    def ref(w, g, h, lr, dec):
        g = jnp.where(dec == 0.0, g, g + dec * w)
        h_new = 0.9 * h + lr * g
        return w - h_new, h_new

    w_ref, h_ref = ref(w, g, h, lr, dec)
    np.testing.assert_array_equal(np.asarray(h2), np.asarray(h_ref))
    np.testing.assert_array_equal(np.asarray(w2), np.asarray(w_ref))


# --------------------------------------------------------------------------- #
# full-step bit-exactness: arena vs per-leaf
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("solver_type", ["SGD", "NESTEROV", "ADAGRAD"])
def test_lenet_step_bitexact(mesh, lenet_net, rng_np, solver_type):
    """SGD+momentum+L2 (the acceptance pin, and Caffe's default) is BIT
    identical arena-vs-per-leaf. Nesterov/AdaGrad run the identical update
    rule (pinned bitwise at the op level by
    test_fused_update_matches_leafwise) but their multi-term step
    expressions give XLA's FMA contraction freedom that can differ between
    the flat and per-leaf fusion shapes — those pin to ~1 ulp instead."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005, solver_type=solver_type)
    params = lenet_net.init(jax.random.PRNGKey(0))
    (p1, s1, m1), (p2, s2, m2) = _ab_step(
        lenet_net, sp, mesh, CommConfig(), params, _batch(rng_np),
        jax.random.PRNGKey(7), n_steps=3)
    assert float(m1["loss"]) == float(m2["loss"])
    if solver_type == "SGD":
        _assert_tree_equal(p1, p2, solver_type)
        _assert_tree_equal(s1.solver.history, s2.solver.history, "history")
    else:
        for l in p1:
            for k in p1[l]:
                np.testing.assert_allclose(
                    np.asarray(p1[l][k]), np.asarray(p2[l][k]),
                    rtol=1e-6, atol=1e-8, err_msg=f"{solver_type} {l}/{k}")


def test_lenet_wire_dtype_and_sum_reduce_bitexact(mesh, lenet_net, rng_np):
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    for comm in (CommConfig(wire_dtype="bf16"), CommConfig(reduce="sum")):
        (p1, _, _), (p2, _, _) = _ab_step(
            lenet_net, sp, mesh, comm, params, _batch(rng_np),
            jax.random.PRNGKey(7))
        _assert_tree_equal(p1, p2, str(comm.wire_dtype))


def test_iter_size_rides_arena_buckets(mesh, lenet_net, rng_np):
    """Gradient accumulation: the post-accumulation sync goes through the
    arena buckets (bit-identical to the per-leaf dense psums), and the
    compiled program carries the bucketed collective count, not
    one-per-leaf — the former 'per-backward comm strategies do not apply'
    warning path."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005)
    params = lenet_net.init(jax.random.PRNGKey(0))
    b = _batch(rng_np)
    stacked = {k: jnp.stack([v, v]) for k, v in b.items()}
    import dataclasses
    comm = CommConfig(arena_bucket_mb=0.05)
    outs = []
    for arena_on in (True, False):
        cc = dataclasses.replace(comm, param_arena=arena_on)
        ts = build_train_step(lenet_net, sp, mesh, cc, iter_size=2,
                              donate=False)
        p, s, m = ts.step(params, init_train_state(params, cc, N_DEV),
                          stacked, jax.random.PRNGKey(7))
        outs.append((ts, p))
    _assert_tree_equal(outs[0][1], outs[1][1], "iter_size")
    ts = outs[0][0]
    hlo = ts.lowerable.lower(params, init_train_state(params, comm, N_DEV),
                             stacked, jax.random.PRNGKey(7)) \
        .compile().as_text()
    bound = math.ceil(ts.arena.total_bytes() / (0.05 * 1e6))
    n = count_gradient_all_reduces(hlo)
    assert 1 <= n <= bound, (n, bound)


def test_scan_steps_bitexact(mesh, lenet_net, rng_np):
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9)
    params = lenet_net.init(jax.random.PRNGKey(0))
    b = _batch(rng_np)
    stacked = {k: jnp.stack([v, v]) for k, v in b.items()}
    import dataclasses
    outs = []
    for arena_on in (True, False):
        cc = dataclasses.replace(CommConfig(), param_arena=arena_on)
        ts = build_train_step(lenet_net, sp, mesh, cc, scan_steps=2,
                              donate=False)
        p, s, m = ts.step(params, init_train_state(params, cc, N_DEV),
                          stacked, jax.random.PRNGKey(7))
        outs.append(p)
    _assert_tree_equal(outs[0], outs[1], "scan")


def test_ssp_arena_bitexact(mesh, lenet_net, rng_np):
    """SSP: fused local update + bucketed boundary delta exchange, across a
    sync boundary, bit-identical local params AND anchor."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005)
    params = lenet_net.init(jax.random.PRNGKey(0))
    b = _batch(rng_np)
    copy = lambda t: jax.tree_util.tree_map(jnp.array, t)  # noqa: E731
    states = []
    for arena_on in (True, False):
        import dataclasses
        cc = dataclasses.replace(CommConfig(arena_bucket_mb=0.05),
                                 param_arena=arena_on)
        ts = build_ssp_train_step(lenet_net, sp, mesh, 1, cc)
        assert (ts.arena is not None) == arena_on
        s = init_ssp_state(copy(params), N_DEV, cc)
        for i in range(4):  # crosses two sync boundaries at staleness 1
            s, m = ts.step(s, b, jax.random.PRNGKey(i))
        states.append(s)
    _assert_tree_equal(states[0].anchor_params, states[1].anchor_params,
                       "anchor")
    for a, bb in zip(jax.tree_util.tree_leaves(states[0].local_params),
                     jax.tree_util.tree_leaves(states[1].local_params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(bb))


def test_dwbp_bucket_request_takes_precedence(mesh, lenet_net):
    """An explicit dwbp_bucket_mb (per-backward chained taps) disables the
    arena on the per-step path — the two bucketing mechanisms never
    double-psum."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed")
    ts = build_train_step(lenet_net, sp, mesh, CommConfig(dwbp_bucket_mb=0),
                          donate=False)
    assert ts.arena is None


# --------------------------------------------------------------------------- #
# AlexNet / GoogLeNet: both numeric policies
# --------------------------------------------------------------------------- #

def _model_net_and_batch(model, image, batch):
    np_ = getattr(zoo, model)(num_classes=10, with_accuracy=False)
    shapes = {"data": (batch // N_DEV, 3, image, image),
              "label": (batch // N_DEV,)}
    net = Net(np_, "TRAIN", source_shapes=shapes)
    rs = np.random.RandomState(0)
    b = {"data": jnp.asarray(rs.randn(batch, 3, image, image)
                             .astype(np.float32)),
         "label": jnp.asarray(rs.randint(0, 10, size=(batch,)))}
    return net, b


def _model_bitexact(mesh, model, image, batch, compute_dtype,
                    check_collectives=False):
    """One full SGD+momentum+L2 optimizer step, arena vs per-leaf: equal
    loss and params equal to <= 1 ulp. (The update RULE is bit-identical —
    pinned by test_fused_update_matches_leafwise and the LeNet full-step
    tests — but at net scale XLA may pick a different cross-replica
    reduction order for a 4 MB bucketed all-reduce than for a 10-element
    per-leaf psum, so individual elements can land 1 ulp apart: the
    observed worst case is 1/5.9M elements at 7e-11 absolute.) Optionally
    also pins the compiled program's gradient all-reduce count against the
    ceil(bytes/bucket) bound — ONE AOT compile serves both the count and
    the run."""
    import dataclasses
    net, b = _model_net_and_batch(model, image, batch)
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005)
    params = net.init(jax.random.PRNGKey(0))
    rng = jax.random.PRNGKey(7)
    results = []
    with config.policy_scope(compute_dtype=compute_dtype):
        for arena_on in (True, False):
            cc = dataclasses.replace(CommConfig(), param_arena=arena_on)
            ts = build_train_step(net, sp, mesh, cc, donate=False)
            state = init_train_state(params, cc, N_DEV)
            compiled = ts.lowerable.lower(params, state, b, rng).compile()
            if arena_on and check_collectives:
                bound = math.ceil(ts.arena.total_bytes() /
                                  (cc.arena_bucket_mb * 1e6))
                n = count_gradient_all_reduces(compiled.as_text())
                assert 1 <= n <= bound, (n, bound)
            # the AOT executable returns the un-wrapped 4-tuple (the jitted
            # fn's dumps slot rides along)
            p, s, m = compiled(params, state, b, rng)[:3]
            results.append((p, s, m))
    (p1, s1, m1), (p2, s2, m2) = results
    assert float(m1["loss"]) == float(m2["loss"])
    for tree1, tree2, what in ((p1, p2, "params"),
                               (s1.solver.history, s2.solver.history,
                                "history")):
        for l in tree1:
            for k in tree1[l]:
                np.testing.assert_allclose(
                    np.asarray(tree1[l][k]), np.asarray(tree2[l][k]),
                    rtol=1e-5, atol=1e-9,
                    err_msg=f"{model} {what} {l}/{k}")


def test_alexnet_step_bitexact_f32(mesh):
    _model_bitexact(mesh, "alexnet", 67, N_DEV, jnp.float32,
                    check_collectives=True)


@pytest.mark.slow
def test_alexnet_step_bitexact_bf16(mesh):
    # fast-lane bf16 coverage lives in test_lenet_bf16_policy_bitexact;
    # the AlexNet bf16 compile is a ~minute of CPU XLA
    _model_bitexact(mesh, "alexnet", 67, N_DEV, jnp.bfloat16)


def test_lenet_bf16_policy_bitexact(mesh, lenet_net, rng_np):
    """bf16-compute policy, fast lane: arena vs per-leaf bit-identical
    (params stay f32; activations/matmuls run bfloat16)."""
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005)
    params = lenet_net.init(jax.random.PRNGKey(0))
    with config.policy_scope(compute_dtype=jnp.bfloat16):
        (p1, s1, m1), (p2, s2, m2) = _ab_step(
            lenet_net, sp, mesh, CommConfig(), params, _batch(rng_np),
            jax.random.PRNGKey(7), n_steps=2)
    assert float(m1["loss"]) == float(m2["loss"])
    _assert_tree_equal(p1, p2, "bf16")
    _assert_tree_equal(s1.solver.history, s2.solver.history, "bf16 hist")


def test_googlenet_bucketed_collective_count(mesh):
    """The acceptance pin, fast-lane half: the data-parallel GoogLeNet
    train step carries <= ceil(total_grad_bytes / arena_bucket_mb)
    gradient all-reduces — ~120 per-leaf psums collapse to ~11 bucketed
    ones at 4 MB (GoogLeNet's ~120-leaf swarm is exactly why the arena
    exists). Counted on the LOWERED program (tracing is seconds; a full
    GoogLeNet XLA CPU compile is minutes): lowering count is an upper
    bound on the compiled count, since XLA merges but never splits
    all-reduces. The compiled-text count (and arena-vs-per-leaf step
    parity, both numeric policies) is pinned by the slow-marked tests
    below and on smaller nets by test_iter_size_rides_arena_buckets /
    the AlexNet f32 test."""
    net, b = _model_net_and_batch("googlenet", 224, N_DEV)
    sp = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                         weight_decay=0.0005)
    params = net.init(jax.random.PRNGKey(0))
    cc = CommConfig()
    ts = build_train_step(net, sp, mesh, cc, donate=False)
    assert ts.arena is not None
    n_leaves = len(jax.tree_util.tree_leaves(params))
    assert n_leaves > 100  # the many-small-tensor regime this PR targets
    bound = math.ceil(ts.arena.total_bytes() / (cc.arena_bucket_mb * 1e6))
    assert ts.arena.n_buckets == bound
    state = init_train_state(params, cc, N_DEV)
    rng = jax.random.PRNGKey(7)
    from poseidon_tpu.runtime.hlo_comm import (
        count_gradient_all_reduces_stablehlo)
    txt = ts.lowerable.lower(params, state, b, rng).as_text()
    n = count_gradient_all_reduces_stablehlo(txt)
    assert 1 <= n <= bound, (n, bound)
    assert n < n_leaves / 4, (n, n_leaves)


@pytest.mark.slow
def test_googlenet_step_bitexact_f32(mesh):
    """Slow-lane half of the acceptance pin: compiled-text collective
    count within the bucket bound + arena-vs-per-leaf step parity."""
    _model_bitexact(mesh, "googlenet", 224, N_DEV, jnp.float32,
                    check_collectives=True)


@pytest.mark.slow
def test_googlenet_step_bitexact_bf16(mesh):
    _model_bitexact(mesh, "googlenet", 224, N_DEV, jnp.bfloat16)
