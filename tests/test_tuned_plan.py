"""TunedPlan tests: resolution precedence, plan-key/provenance refusal,
store corruption tolerance, the trial-hygiene estimator, tune smoke
persist + memo-hit, and the anchor — BITWISE training parity between a
run with an auto-loaded plan and the same run with the equivalent
explicit flags (the resolution layer must be a pure re-router of values,
never a second code path)."""

import dataclasses
import json
import os
import time

import numpy as np
import pytest

SMALLNET = """
name: "PlanNet"
layers {
  name: "src" type: MEMORY_DATA top: "data" top: "label"
  memory_data_param { batch_size: 8 channels: 1 height: 12 width: 12 }
}
layers {
  name: "conv1" type: CONVOLUTION bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 3
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "relu1" type: RELU bottom: "conv1" top: "conv1" }
layers { name: "pool1" type: POOLING bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layers {
  name: "ip1" type: INNER_PRODUCT bottom: "pool1" top: "ip1"
  inner_product_param { num_output: 5
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "loss" type: SOFTMAX_LOSS bottom: "ip1" bottom: "label"
  top: "loss" }
"""


@pytest.fixture
def policy_guard():
    """Snapshot/restore every piece of process-global state the plan
    resolution layer touches, so these tests cannot leak policy into the
    rest of the suite."""
    from poseidon_tpu import config
    from poseidon_tpu.runtime import tuned_plan as tp

    pol = config.policy()
    saved_policy = {"conv_layout": pol.conv_layout,
                    "conv_strategy": pol.conv_strategy}
    saved_pipe = dataclasses.asdict(config.pipeline_config())
    saved_cc = config.compile_cache_config().cache_dir
    saved_active = tp.active_resolution()
    yield
    config.set_policy(**saved_policy)
    config.set_pipeline_config(**saved_pipe)
    config.set_compile_cache_config(cache_dir=saved_cc)
    tp.set_active_resolution(saved_active)


def _plan_doc(model, knobs, **overrides):
    """A store-shaped plan doc whose provenance matches THIS process."""
    import jax
    from poseidon_tpu.runtime import tuned_plan as tp

    doc = {
        "version": tp.PLAN_VERSION,
        "model": model,
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "jax_version": jax.__version__,
        "n_devices": jax.device_count(),
        "key": tp.plan_key(model, jax.default_backend(),
                           jax.device_count()),
        "knobs": knobs,
        "trials": {},
        "measured_at": "2026-01-01T00:00:00Z",
        "search_cost_s": 1.0,
    }
    doc.update(overrides)
    return doc


# --------------------------------------------------------------------------- #
# resolution precedence + provenance
# --------------------------------------------------------------------------- #

def test_resolution_precedence_flag_plan_default():
    from poseidon_tpu.runtime import tuned_plan as tp

    doc = {"knobs": {"conv_layout": "NHWC", "arena_bucket_mb": 1.0},
           "key": "k" * 32}
    res = tp.resolve(doc, {"conv_layout": "NCHW"})
    # explicit flag > plan
    assert res.values["conv_layout"] == "NCHW"
    assert res.sources["conv_layout"] == "flag"
    # plan > built-in default
    assert res.values["arena_bucket_mb"] == 1.0
    assert res.sources["arena_bucket_mb"] == "plan"
    # built-in default bats last
    assert res.values["device_prefetch"] == \
        tp.BUILTIN_DEFAULTS["device_prefetch"]
    assert res.sources["device_prefetch"] == "default"
    # the shadowed measured winner is recorded as an override
    assert res.overridden == ["conv_layout"]
    prov = res.provenance()
    assert prov["conv_layout"] == "NCHW (flag)"
    assert prov["arena_bucket_mb"] == "1.0 (plan)"
    assert prov["overridden_by_flags"] == "conv_layout"


def test_resolution_without_plan_is_all_defaults(policy_guard):
    from poseidon_tpu.runtime import tuned_plan as tp

    res = tp.resolve(None, {}, store="/somewhere/we/looked")
    assert set(res.values) == set(tp.TRAIN_KNOBS)
    assert all(src == "default" for src in res.sources.values())
    assert res.overridden == []
    assert "plan_key" not in res.provenance()
    # a defaults-only resolution must NOT publish a store for conv_tune's
    # fallback — only an actually-loaded plan routes the per-layer store
    tp.set_active_resolution(res)
    assert tp.active_store_dir() == ""
    doc = {"knobs": {}, "key": "k" * 32}
    tp.set_active_resolution(tp.resolve(doc, {}, store="/plan/store"))
    assert tp.active_store_dir() == "/plan/store"


# --------------------------------------------------------------------------- #
# plan-key / provenance mismatch refuses to auto-load, loudly
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("field,value", [("device_kind", "TPU v5e"),
                                         ("jax_version", "9.9.9")])
def test_plan_refuses_mismatched_provenance(tmp_path, capsys, field, value):
    from poseidon_tpu.runtime import tuned_plan as tp

    doc = _plan_doc("mismatch", {"conv_layout": "NHWC"}, **{field: value})
    tp.save_plan(doc, cache_dir=str(tmp_path))
    assert tp.load_plan("mismatch", cache_dir=str(tmp_path)) is None
    out = capsys.readouterr().out
    assert "REFUSING" in out and field in out
    # ...and the matching provenance loads fine
    good = _plan_doc("mismatch", {"conv_layout": "NHWC"})
    tp.save_plan(good, cache_dir=str(tmp_path))
    loaded = tp.load_plan("mismatch", cache_dir=str(tmp_path))
    assert loaded is not None and loaded["knobs"]["conv_layout"] == "NHWC"


def test_different_backend_or_devices_is_a_clean_miss(tmp_path):
    from poseidon_tpu.runtime import tuned_plan as tp

    doc = _plan_doc("missy", {"conv_layout": "NHWC"})
    tp.save_plan(doc, cache_dir=str(tmp_path))
    # a different device COUNT keys to a different plan: miss, defaults
    assert tp.load_plan("missy", n_devices=2 ** 14,
                        cache_dir=str(tmp_path)) is None
    # different model name: miss
    assert tp.load_plan("other", cache_dir=str(tmp_path)) is None


# --------------------------------------------------------------------------- #
# tuned store satellites: atomic save, torn-entry tolerance (loud)
# --------------------------------------------------------------------------- #

def test_save_tuned_atomic_no_tmp_litter(tmp_path):
    from poseidon_tpu.runtime.compile_cache import (load_tuned, save_tuned,
                                                    tuned_path)

    path = save_tuned(str(tmp_path), "ns", "k1", {"winner": "x"})
    assert path == tuned_path(str(tmp_path), "ns", "k1")
    litter = [n for n in os.listdir(os.path.dirname(path)) if ".tmp" in n]
    assert litter == []
    assert load_tuned(str(tmp_path), "ns", "k1") == {"winner": "x"}


def test_load_tuned_torn_entry_is_loud_miss(tmp_path, capsys):
    from poseidon_tpu.runtime.compile_cache import (load_tuned, save_tuned,
                                                    tuned_path)

    save_tuned(str(tmp_path), "ns", "k2", {"winner": "x"})
    with open(tuned_path(str(tmp_path), "ns", "k2"), "w") as f:
        f.write('{"winner": "x"')          # torn mid-write
    assert load_tuned(str(tmp_path), "ns", "k2") is None
    assert "torn/unreadable" in capsys.readouterr().out
    # a clean miss (no file at all) stays silent
    assert load_tuned(str(tmp_path), "ns", "nope") is None
    assert "torn" not in capsys.readouterr().out


# --------------------------------------------------------------------------- #
# trial hygiene: warm-up + interleaved min-of-k
# --------------------------------------------------------------------------- #

def test_interleaved_estimator_not_fooled_by_first_call_cost():
    """A candidate whose first calls pay a large one-time cost (compile
    noise) but which is genuinely fastest afterwards must WIN: the warm-up
    calls absorb the one-time cost before any timing starts. This is the
    conv_tune trial-hygiene contract."""
    from poseidon_tpu.runtime.tuned_plan import interleaved_min_ms

    calls = {"compiley": 0, "steady": 0}

    def compiley():
        calls["compiley"] += 1
        time.sleep(0.05 if calls["compiley"] <= 2 else 0.001)

    def steady():
        calls["steady"] += 1
        time.sleep(0.005)

    ms = interleaved_min_ms({"compiley": compiley, "steady": steady},
                            windows=3, iters=2, warmup=2)
    assert ms["compiley"] < ms["steady"]
    # warm-up ran before timing: the 2 expensive calls were absorbed
    assert calls["compiley"] >= 2 + 3 * 2


def test_conv_tune_resolve_uses_interleaved_hygiene(monkeypatch, tmp_path):
    """conv_tune's measurement must route through the shared estimator
    (warm-up + interleaved windows), not per-candidate sequential loops."""
    from poseidon_tpu.ops import conv_tune
    from poseidon_tpu.runtime import tuned_plan as tp

    seen = {}
    real = tp.interleaved_min_ms

    def spy(fns, **kw):
        seen["candidates"] = sorted(fns)
        seen["kw"] = kw
        return real(fns, **kw)

    monkeypatch.setattr(tp, "interleaved_min_ms", spy)
    conv_tune.clear_memo()
    doc = conv_tune.resolve("convH", c=3, h=10, w=10, kernel=(3, 3),
                            stride=(1, 1), pad=(0, 0), group=1, out_ch=4,
                            layout="NCHW", batch=4,
                            cache_dir=str(tmp_path))
    conv_tune.clear_memo()
    assert doc["source"] == "measured"
    assert seen["candidates"] == sorted(doc["timings_ms"])
    assert seen["kw"]["warmup"] == conv_tune.TRIAL_WARMUP >= 2
    assert seen["kw"]["windows"] == conv_tune.TRIAL_WINDOWS >= 2
    assert doc["winner"] == min(doc["timings_ms"],
                                key=doc["timings_ms"].get)


# --------------------------------------------------------------------------- #
# conv_layout "auto": the measured plan row replaces the builtin table
# --------------------------------------------------------------------------- #

def test_conv_layout_auto_consults_active_plan(policy_guard):
    from poseidon_tpu.numeric import resolve_conv_layout
    from poseidon_tpu.runtime import tuned_plan as tp

    assert resolve_conv_layout("auto", backend="cpu") == "NCHW"
    doc = {"knobs": {"conv_layout": "NHWC"}, "key": "k" * 32}
    tp.set_active_resolution(tp.resolve(doc, {}))
    # the measured row IS the auto answer now
    assert resolve_conv_layout("auto", backend="cpu") == "NHWC"
    # the tune search builds its default arm against the builtin table
    assert resolve_conv_layout("auto", backend="cpu",
                               consult_plan=False) == "NCHW"
    # explicit layouts never consult the plan
    assert resolve_conv_layout("NCHW", backend="cpu") == "NCHW"
    # a flag-sourced resolution is not a measured row
    tp.set_active_resolution(tp.resolve(doc, {"conv_layout": "NCHW"}))
    assert tp.active_plan_value("conv_layout") is None
    tp.set_active_resolution(None)
    assert resolve_conv_layout("auto", backend="cpu") == "NCHW"


# --------------------------------------------------------------------------- #
# tune smoke: persists a plan, second run memo-hits and skips measurement
# --------------------------------------------------------------------------- #

def test_tune_smoke_persists_then_memo_hits(tmp_path, policy_guard):
    from poseidon_tpu.proto.messages import load_net_from_string
    from poseidon_tpu.runtime import tuned_plan as tp

    net_param = load_net_from_string(SMALLNET)
    shapes = {"data": (8, 1, 12, 12), "label": (8,)}
    r = tp.run_tune("plannet", smoke=True, cache_dir=str(tmp_path),
                    net_param=net_param, source_shapes=shapes,
                    knobs=["conv_layout"], windows=1, iters=1)
    assert r["source"] == "measured"
    doc = r["doc"]
    # the artifact is complete: every knob resolved, provenance stamped
    assert set(doc["knobs"]) == set(tp.BUILTIN_DEFAULTS)
    assert doc["trials"]["conv_layout"]["source"] == "measured"
    assert set(doc["trials"]["conv_layout"]["timings_ms"]) == \
        {"NCHW", "NHWC"}
    assert doc["ab"]["speedup"] >= 1.0        # default is always a candidate
    # restricted knobs are RECORDED, never silently capped
    assert "pipeline" in doc["skipped"]
    assert doc["device_kind"] and doc["jax_version"]
    assert os.path.exists(r["path"])
    with open(r["path"]) as f:
        assert json.load(f)["key"] == doc["key"]
    # second run: memo-hit, no re-measurement
    t0 = time.perf_counter()
    r2 = tp.run_tune("plannet", smoke=True, cache_dir=str(tmp_path))
    assert r2["source"] == "persisted"
    assert r2["doc"]["key"] == doc["key"]
    assert time.perf_counter() - t0 < 5.0     # loaded, not measured
    # --force re-measures
    r3 = tp.run_tune("plannet", smoke=True, cache_dir=str(tmp_path),
                     net_param=net_param, source_shapes=shapes,
                     knobs=["conv_layout"], windows=1, iters=1, force=True)
    assert r3["source"] == "measured"


# --------------------------------------------------------------------------- #
# the anchor: auto-loaded plan == equivalent explicit flags, BITWISE
# --------------------------------------------------------------------------- #

def _memory_data(n=192, seed=0):
    rs = np.random.RandomState(seed)
    templates = rs.randn(5, 1, 12, 12).astype(np.float32)
    labels = rs.randint(0, 5, size=n)
    data = templates[labels] + \
        0.25 * rs.randn(n, 1, 12, 12).astype(np.float32)
    return {"data": data, "label": labels}


def _train_leaves(tmp_path, sub, engine_kw):
    import jax
    from poseidon_tpu.parallel import CommConfig
    from poseidon_tpu.proto.messages import (SolverParameter,
                                             load_net_from_string)
    from poseidon_tpu.runtime.engine import Engine

    out = tmp_path / sub
    out.mkdir()
    sp = SolverParameter(train_net_param=load_net_from_string(SMALLNET),
                         base_lr=0.05, lr_policy="fixed", momentum=0.9,
                         weight_decay=5e-4, display=0, max_iter=8,
                         random_seed=3)
    comm = CommConfig(param_arena=True,
                      arena_bucket_mb=engine_kw.pop("arena_bucket_mb"))
    eng = Engine(sp, comm=comm, memory_data=_memory_data(),
                 output_dir=str(out), **engine_kw)
    try:
        eng.train()
        return [np.asarray(v).copy()
                for v in jax.tree_util.tree_leaves(eng.params)]
    finally:
        eng.close()


def test_autoloaded_plan_bitwise_equals_explicit_flags(tmp_path,
                                                       policy_guard):
    """The acceptance anchor: a training run whose knobs came from an
    auto-loaded TunedPlan must be BITWISE identical to the same run with
    the equivalent explicit flags — plan resolution re-routes values, it
    is never a second code path."""
    from poseidon_tpu import config
    from poseidon_tpu.runtime import tuned_plan as tp

    knobs = {"conv_layout": "NHWC", "conv_strategy": "",
             "arena_bucket_mb": 1.0, "mesh": "",
             "device_prefetch": 0, "max_in_flight": 1,
             "steps_per_dispatch": 1, "wire_dtype": "",
             "remat": "", "hbm_budget_gb": 0.0,
             "serve_buckets": tp.BUILTIN_DEFAULTS["serve_buckets"]}
    store = tmp_path / "store"
    tp.save_plan(_plan_doc("plannet", knobs), cache_dir=str(store))

    # arm A: the cmd_train path — load, resolve (no flags), apply
    doc = tp.load_plan("plannet", cache_dir=str(store))
    assert doc is not None
    res = tp.resolve(doc, {}, store=str(store))
    assert all(res.sources[k] == "plan" for k in tp.TRAIN_KNOBS)
    eng_kw = tp.apply_training_resolution(res)
    assert tp.active_resolution() is res
    leaves_plan = _train_leaves(tmp_path, "via_plan", {
        "arena_bucket_mb": eng_kw["arena_bucket_mb"],
        "device_prefetch": eng_kw["device_prefetch"],
        "max_in_flight": eng_kw["max_in_flight"],
        "steps_per_dispatch": eng_kw["steps_per_dispatch"]})

    # arm B: the same knobs as explicit settings, no plan anywhere
    tp.set_active_resolution(None)
    config.set_policy(conv_layout="NHWC")
    leaves_flags = _train_leaves(tmp_path, "via_flags", {
        "arena_bucket_mb": 1.0, "device_prefetch": 0, "max_in_flight": 1,
        "steps_per_dispatch": 1})

    assert len(leaves_plan) == len(leaves_flags)
    for a, b in zip(leaves_plan, leaves_flags):
        np.testing.assert_array_equal(a, b)


def test_engine_writes_plan_provenance_section(tmp_path, policy_guard):
    """A run with an active resolution carries the tuned_plan section —
    values, sources, and overrides — into stats.yaml."""
    from poseidon_tpu.runtime import tuned_plan as tp

    knobs = {"conv_layout": "NCHW", "conv_strategy": "",
             "arena_bucket_mb": 4.0, "mesh": "", "device_prefetch": 0,
             "max_in_flight": 1, "steps_per_dispatch": 1,
             "serve_buckets": tp.BUILTIN_DEFAULTS["serve_buckets"]}
    doc = _plan_doc("plannet", knobs)
    res = tp.resolve(doc, {"max_in_flight": 1}, store=str(tmp_path))
    tp.apply_training_resolution(res)
    _train_leaves(tmp_path, "prov", {
        "arena_bucket_mb": 4.0, "device_prefetch": 0, "max_in_flight": 1,
        "steps_per_dispatch": 1})
    stats = (tmp_path / "prov" / "stats.yaml").read_text()
    assert "tuned_plan:" in stats
    assert "conv_layout: NCHW (plan)" in stats
    assert "max_in_flight: 1 (flag)" in stats
    assert f"plan_key: {doc['key']}" in stats
