"""enable_tpu_async_collectives: per-flag honoring of LIBTPU_INIT_ARGS.

Advisor finding (config.py:62, round 6): the old guard only looked for the
FUSION flag substring — a user who set ``--xla_enable_async_all_reduce=
false`` (but not the fusion flag) got BOTH flags appended, handing libtpu
a conflicting duplicate of their explicit choice. Each flag must be
checked independently: explicit values are honored in either polarity and
never duplicated; any explicit =false marks a deliberate baseline run and
nothing is appended at all.
"""

import re

import pytest

from poseidon_tpu.config import (_ASYNC_COLLECTIVE_FLAGS, _flag_value,
                                 enable_tpu_async_collectives)

FUSE, ASYNC = _ASYNC_COLLECTIVE_FLAGS


def _count(args: str, name: str) -> int:
    return len(re.findall(r"--%s=" % re.escape(name), args))


CASES = [
    # (existing LIBTPU_INIT_ARGS, expected return,
    #  expected fuse value, expected async value)
    ("", True, True, True),
    (f"--{FUSE}=true", True, True, True),
    (f"--{ASYNC}=true", True, True, True),
    (f"--{FUSE}=true --{ASYNC}=true", True, True, True),
    # the advisor's exact case: explicit async=false must NOT gain a
    # conflicting duplicate (old code appended both flags here)
    (f"--{ASYNC}=false", False, None, False),
    (f"--{FUSE}=false", False, False, None),
    (f"--{FUSE}=false --{ASYNC}=false", False, False, False),
    (f"--{FUSE}=true --{ASYNC}=false", False, True, False),
    # unrelated flags ride along untouched
    (f"--xla_tpu_foo=7 --{ASYNC}=false", False, None, False),
    ("--xla_tpu_foo=7", True, True, True),
]


@pytest.mark.parametrize("existing,expect_ret,expect_fuse,expect_async",
                         CASES)
def test_async_collective_flag_merge(monkeypatch, existing, expect_ret,
                                     expect_fuse, expect_async):
    monkeypatch.setenv("LIBTPU_INIT_ARGS", existing)
    ret = enable_tpu_async_collectives(check_backend=False)
    assert ret is expect_ret
    after = __import__("os").environ["LIBTPU_INIT_ARGS"]
    for name, expect in ((FUSE, expect_fuse), (ASYNC, expect_async)):
        # NEVER a duplicate — the satellite's contract
        assert _count(after, name) <= 1, after
        assert _flag_value(after, name) is expect, (name, after)
    # pre-existing unrelated args survive verbatim
    for tok in existing.split():
        assert tok in after


def test_explicit_false_leaves_env_untouched(monkeypatch):
    existing = f"--{ASYNC}=false --xla_tpu_bar=1"
    monkeypatch.setenv("LIBTPU_INIT_ARGS", existing)
    assert enable_tpu_async_collectives(check_backend=False) is False
    assert __import__("os").environ["LIBTPU_INIT_ARGS"] == existing


def test_flag_value_last_occurrence_wins():
    args = f"--{ASYNC}=false --{ASYNC}=true"
    assert _flag_value(args, ASYNC) is True
    assert _flag_value(args, FUSE) is None
    assert _flag_value(f"--{ASYNC}=1", ASYNC) is True
    assert _flag_value(f"--{ASYNC}=0", ASYNC) is False


def test_backend_guard_blocks_late_append(monkeypatch):
    """With jax's backend already initialized (true in this test process),
    the default call must refuse to mutate LIBTPU_INIT_ARGS when it would
    need to append."""
    import jax

    jax.devices()  # force backend init
    monkeypatch.setenv("LIBTPU_INIT_ARGS", "")
    assert enable_tpu_async_collectives() is False
    assert __import__("os").environ["LIBTPU_INIT_ARGS"] == ""
