"""LevelDB reader vs a hand-built, format-spec-derived database fixture.

The round-1 gap: data/leveldb_reader.py was validated only against its own
writer, so a shared misunderstanding of the format would be invisible. No
stock LevelDB exists in this image, so this fixture is built here from the
PUBLIC on-disk format documentation (leveldb's doc/table_format.md,
doc/log_format.md, db/dbformat.h semantics) with fresh encoding code —
deliberately NOT importing the repo's writer — including the corners stock
databases exhibit that the repo writer never produces:

- prefix-compressed keys with restart interval 2 (writer uses full restarts)
- a mixed table: one raw block and one snappy block in the same file
- proper masked-CRC32C slots in both table blocks and log records
- a log record fragmented FIRST/MIDDLE/LAST across 32 KiB block boundaries
- a MANIFEST whose VersionEdits add AND delete files (compaction history):
  an obsolete .ldb left on disk must be ignored
- deletions and overwrites resolved by sequence number across table + WAL
"""

import struct

import pytest

from poseidon_tpu.data.leveldb_reader import LevelDBReader

TABLE_MAGIC = 0xDB4775248B80FB57
TYPE_DELETION, TYPE_VALUE = 0, 1
LOG_BLOCK = 32768
FULL, FIRST, MIDDLE, LAST = 1, 2, 3, 4


# ---- independent primitives (from the format docs, not the repo code) ---- #

def varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


_CRC_TBL = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 if _c & 1 else 0)
    _CRC_TBL.append(_c)


def crc32c(data: bytes, seed: int = 0) -> int:
    c = seed ^ 0xFFFFFFFF
    for b in data:
        c = (c >> 8) ^ _CRC_TBL[(c ^ b) & 0xFF]
    return c ^ 0xFFFFFFFF


def mask_crc(c: int) -> int:
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def ikey(user_key: bytes, seq: int, typ: int = TYPE_VALUE) -> bytes:
    return user_key + struct.pack("<Q", (seq << 8) | typ)


def build_block(entries, restart_interval: int) -> bytes:
    """Prefix-compressed block: entries sorted, restart points every
    ``restart_interval`` entries, restart-offset array + count trailer."""
    out = bytearray()
    restarts = []
    prev = b""
    for i, (key, value) in enumerate(entries):
        if i % restart_interval == 0:
            restarts.append(len(out))
            shared = 0
        else:
            shared = 0
            while shared < min(len(prev), len(key)) and \
                    prev[shared] == key[shared]:
                shared += 1
        out += varint(shared) + varint(len(key) - shared) + \
            varint(len(value))
        out += key[shared:] + value
        prev = key
    for r in restarts:
        out += struct.pack("<I", r)
    out += struct.pack("<I", len(restarts))
    return bytes(out)


def emit_block(f, raw: bytes, compress: bool) -> tuple:
    """block contents + 1-byte type + 4-byte masked crc; returns handle."""
    if compress:
        from poseidon_tpu.data.snappy import compress as snappy_compress
        data, btype = snappy_compress(raw), 1
    else:
        data, btype = raw, 0
    off = f.tell()
    f.write(data)
    f.write(bytes([btype]))
    f.write(struct.pack("<I", mask_crc(crc32c(data + bytes([btype])))))
    return off, len(data)


def handle_enc(off: int, size: int) -> bytes:
    return varint(off) + varint(size)


def write_sstable(path: str, kvs, restart_interval=2, split_at=None,
                  compress_second=True):
    """kvs: sorted [(internal_key, value)]; two data blocks when split_at."""
    split_at = split_at if split_at is not None else len(kvs)
    with open(path, "wb") as f:
        handles = []
        for part in (kvs[:split_at], kvs[split_at:]):
            if not part:
                continue
            raw = build_block(part, restart_interval)
            handles.append((emit_block(f, raw, compress_second and
                                       len(handles) == 1), part[-1][0]))
        meta_handle = emit_block(f, build_block([], 1), False)
        index_entries = [(last_key + b"\x00", handle_enc(*h))
                         for h, last_key in handles]
        index_handle = emit_block(f, build_block(index_entries, 1), False)
        footer = handle_enc(*meta_handle) + handle_enc(*index_handle)
        footer += b"\x00" * (40 - len(footer))
        footer += struct.pack("<Q", TABLE_MAGIC)
        f.write(footer)


class LogWriter:
    """log_format.md framing: 32 KiB blocks, 7-byte headers
    (crc32c masked over type+payload, little-endian length, type)."""

    def __init__(self, path: str):
        self.f = open(path, "wb")
        self.pos = 0

    def add(self, record: bytes):
        first = True
        while True:
            left = LOG_BLOCK - (self.pos % LOG_BLOCK)
            if left < 7:
                self.f.write(b"\x00" * left)
                self.pos += left
                continue
            avail = left - 7
            frag = record[:avail]
            record = record[avail:]
            if first and not record:
                t = FULL
            elif first:
                t = FIRST
            elif record:
                t = MIDDLE
            else:
                t = LAST
            crc = mask_crc(crc32c(bytes([t]) + frag))
            self.f.write(struct.pack("<IHB", crc, len(frag), t))
            self.f.write(frag)
            self.pos += 7 + len(frag)
            first = False
            if not record:
                return

    def close(self):
        self.f.close()


def write_batch(seq: int, ops) -> bytes:
    """WriteBatch: 8B seq, 4B count, then per-op tag + varint-framed data."""
    out = bytearray(struct.pack("<QI", seq, len(ops)))
    for op in ops:
        if op[0] == "put":
            _, k, v = op
            out += bytes([TYPE_VALUE]) + varint(len(k)) + k + \
                varint(len(v)) + v
        else:
            _, k = op
            out += bytes([TYPE_DELETION]) + varint(len(k)) + k
    return bytes(out)


def version_edit(comparator=None, log_number=None, next_file=None,
                 last_seq=None, new_files=(), deleted_files=()) -> bytes:
    out = bytearray()
    if comparator is not None:
        out += varint(1) + varint(len(comparator)) + comparator
    if log_number is not None:
        out += varint(2) + varint(log_number)
    if next_file is not None:
        out += varint(3) + varint(next_file)
    if last_seq is not None:
        out += varint(4) + varint(last_seq)
    for level, num in deleted_files:
        out += varint(6) + varint(level) + varint(num)
    for level, num, size, smallest, largest in new_files:
        out += varint(7) + varint(level) + varint(num) + varint(size)
        out += varint(len(smallest)) + smallest
        out += varint(len(largest)) + largest
    return bytes(out)


# ------------------------------ fixtures --------------------------------- #

@pytest.fixture()
def stock_like_db(tmp_path):
    """A directory shaped like a stock DB mid-life: one live compacted
    table, one obsolete table still on disk, and a WAL with overwrites,
    a deletion, and a >32 KiB fragmented record."""
    db = tmp_path / "db"
    db.mkdir()

    # live table 000005.ldb: 5 keys, 2 blocks (one raw, one snappy),
    # restart interval 2 so prefix compression is actually exercised
    live_kvs = [
        (ikey(b"apple", 10), b"red"),
        (ikey(b"apricot", 11), b"orange"),
        (ikey(b"banana", 12), b"yellow"),
        (ikey(b"cherry", 13), b"darkred"),
        (ikey(b"damson", 14), b"purple"),
    ]
    write_sstable(str(db / "000005.ldb"), live_kvs, split_at=3)

    # obsolete table 000003.ldb: would poison 'apple' if wrongly read
    write_sstable(str(db / "000003.ldb"),
                  [(ikey(b"apple", 2), b"WRONG-OBSOLETE")],
                  compress_second=False)

    # MANIFEST: edit 1 creates 3, edit 2 compacts 3 away and adds 5
    mw = LogWriter(str(db / "MANIFEST-000007"))
    mw.add(version_edit(comparator=b"leveldb.BytewiseComparator",
                        log_number=4, next_file=6, last_seq=14,
                        new_files=[(0, 3, 64, ikey(b"apple", 2),
                                    ikey(b"apple", 2))]))
    mw.add(version_edit(log_number=6, next_file=8, last_seq=14,
                        deleted_files=[(0, 3)],
                        new_files=[(0, 5, 256, live_kvs[0][0],
                                    live_kvs[-1][0])]))
    mw.close()
    (db / "CURRENT").write_text("MANIFEST-000007\n")

    # WAL 000006.log: overwrite banana, delete cherry, add big + elder
    big = bytes(40000)  # forces FIRST/MIDDLE/LAST fragmentation
    lw = LogWriter(str(db / "000006.log"))
    lw.add(write_batch(20, [("put", b"banana", b"green"),
                            ("del", b"cherry")]))
    lw.add(write_batch(22, [("put", b"elder", b"black"),
                            ("put", b"big", big)]))
    lw.close()

    # an old, superseded WAL (< log_number 6) that must be ignored
    lw2 = LogWriter(str(db / "000004.log"))
    lw2.add(write_batch(1, [("put", b"apple", b"WRONG-OLD-WAL")]))
    lw2.close()

    want = {
        b"apple": b"red",
        b"apricot": b"orange",
        b"banana": b"green",       # WAL overwrote the table value
        b"damson": b"purple",
        b"elder": b"black",
        b"big": big,
    }                               # cherry deleted
    return str(db), want


def test_reader_matches_spec_fixture(stock_like_db):
    path, want = stock_like_db
    r = LevelDBReader(path)
    got = dict(iter(r))
    assert got == want
    assert len(r) == len(want)
    # sorted key order (bytewise comparator)
    assert [r.key_at(i) for i in range(len(r))] == sorted(want)
    for i, k in enumerate(sorted(want)):
        assert r.value_at(i) == want[k], k


def test_reader_wal_only_state(tmp_path):
    """A DB that crashed before any flush: just a log, no CURRENT."""
    db = tmp_path / "walonly"
    db.mkdir()
    lw = LogWriter(str(db / "000003.log"))
    lw.add(write_batch(1, [("put", b"k1", b"v1"), ("put", b"k2", b"v2")]))
    lw.add(write_batch(3, [("del", b"k1"), ("put", b"k3", b"v3")]))
    lw.close()
    r = LevelDBReader(str(db))
    assert dict(iter(r)) == {b"k2": b"v2", b"k3": b"v3"}


def test_convert_db_from_spec_fixture(stock_like_db, tmp_path):
    """The dataset tool chain consumes the stock-shaped DB end to end."""
    from poseidon_tpu.runtime.tools import convert_db
    from poseidon_tpu.data.lmdb_reader import LMDBReader
    path, want = stock_like_db
    out = str(tmp_path / "as_lmdb")
    n = convert_db(path, out, "LMDB")
    assert n == len(want)
    lr = LMDBReader(out)
    assert {lr.key_at(i): lr.value_at(i) for i in range(len(lr))} == want


# ------------------- multi-level compacted database ----------------------- #

def version_edit_cp(compact_pointers=(), **kw) -> bytes:
    """version_edit + tag-5 compact pointers (level, internal key) — present
    in any MANIFEST that has survived a compaction."""
    out = bytearray(version_edit(**kw))
    for level, ik in compact_pointers:
        out += varint(5) + varint(level) + varint(len(ik)) + ik
    return bytes(out)


@pytest.fixture()
def multilevel_db(tmp_path):
    """A database shaped like stock LevelDB after real compaction traffic:

    - a bottom level-2 run whose entries carry sequence 0 (leveldb zeroes
      the sequence of bottom-level keys during compaction when no snapshot
      needs them — db/version_set semantics)
    - a level-1 run holding a tombstone for a key whose value lives below
      it, plus an overwrite shadowing a level-2 value
    - two OVERLAPPING level-0 files (level 0 is the only level allowed to
      overlap) where the same user key appears in both — highest sequence
      must win regardless of file scan order
    - delete-then-reinsert across levels: value@L2, tombstone@L1,
      new value@L0 — the key must be PRESENT with the newest value
    - a WAL overwriting and deleting on top of all levels
    - a MANIFEST with multi-record compaction history: comparator, compact
      pointers, an obsolete level-1 file deleted by a later edit but still
      on disk (must be ignored)
    """
    db = tmp_path / "db"
    db.mkdir()

    # bottom level 2: sequences zeroed by compaction
    l2 = [
        (ikey(b"alpha", 0), b"a-bottom"),
        (ikey(b"dead", 0), b"should-die"),
        (ikey(b"ghost", 0), b"g-old"),
        (ikey(b"keep", 0), b"base"),
        (ikey(b"over", 0), b"old"),
    ]
    write_sstable(str(db / "000011.ldb"), l2, split_at=3)

    # level 1: tombstone for 'ghost' + overwrite of 'over' + new 'lime'
    l1 = [
        (ikey(b"ghost", 20, TYPE_DELETION), b""),
        (ikey(b"lime", 22), b"green"),
        (ikey(b"over", 21), b"mid"),
    ]
    write_sstable(str(db / "000013.ldb"), l1, compress_second=False)

    # overlapping level-0 files: same user key in both, newer seq wins;
    # 000017 also re-inserts 'ghost' ABOVE the level-1 tombstone
    l0_old = [
        (ikey(b"alpha", 40), b"a0-old"),
        (ikey(b"dead", 41, TYPE_DELETION), b""),
    ]
    write_sstable(str(db / "000015.ldb"), l0_old, compress_second=False)
    l0_new = [
        (ikey(b"alpha", 60), b"a0-new"),
        (ikey(b"ghost", 61), b"resurrected"),
    ]
    write_sstable(str(db / "000017.ldb"), l0_new, compress_second=False)

    # an LDB compacted away but still on disk: wrong values for everything
    write_sstable(str(db / "000009.ldb"),
                  [(ikey(b"alpha", 5), b"WRONG-OBSOLETE")],
                  compress_second=False)

    # MANIFEST: three edits — creation, compaction to levels, L0 additions
    mw = LogWriter(str(db / "MANIFEST-000020"))
    mw.add(version_edit_cp(comparator=b"leveldb.BytewiseComparator",
                           log_number=8, next_file=12, last_seq=10,
                           new_files=[(1, 9, 64, ikey(b"alpha", 5),
                                       ikey(b"alpha", 5))]))
    mw.add(version_edit_cp(log_number=14, next_file=16, last_seq=30,
                           deleted_files=[(1, 9)],
                           new_files=[(2, 11, 256, l2[0][0], l2[-1][0]),
                                      (1, 13, 128, l1[0][0], l1[-1][0])],
                           compact_pointers=[(1, ikey(b"over", 21)),
                                             (2, ikey(b"over", 0))]))
    mw.add(version_edit_cp(log_number=18, next_file=21, last_seq=61,
                           new_files=[(0, 15, 64, l0_old[0][0],
                                       l0_old[-1][0]),
                                      (0, 17, 64, l0_new[0][0],
                                       l0_new[-1][0])]))
    mw.close()
    (db / "CURRENT").write_text("MANIFEST-000020\n")

    # live WAL on top of all levels
    lw = LogWriter(str(db / "000018.log"))
    lw.add(write_batch(70, [("put", b"keep", b"fresh"),
                            ("del", b"lime")]))
    lw.close()
    # superseded WAL (< log_number 18), still on disk
    lw2 = LogWriter(str(db / "000008.log"))
    lw2.add(write_batch(1, [("put", b"keep", b"WRONG-OLD-WAL")]))
    lw2.close()

    want = {
        b"alpha": b"a0-new",       # overlapping-L0 race: seq 60 beats 40, 0
        b"ghost": b"resurrected",  # value@L2 < tombstone@L1 < value@L0
        b"keep": b"fresh",         # WAL overwrite of a seq-0 bottom entry
        b"over": b"mid",           # L1 shadows L2
    }                              # dead: L0 tombstone kills L2 value
                                   # lime: WAL tombstone kills L1 value
    return str(db), want


def test_reader_multilevel_compacted(multilevel_db):
    path, want = multilevel_db
    r = LevelDBReader(path)
    got = dict(iter(r))
    assert got == want
    assert len(r) == len(want)
    # deleted keys are really gone, not empty
    for k in (b"dead", b"lime"):
        assert k not in got


def test_multilevel_tables_accepted_by_convert(multilevel_db, tmp_path):
    """The merged multi-level view round-trips through the LMDB converter
    path (convert_db uses the reader's sorted iteration)."""
    from poseidon_tpu.data.lmdb_reader import LMDBReader, LMDBWriter
    path, want = multilevel_db
    out = tmp_path / "out_lmdb"
    w = LMDBWriter(str(out))
    for k, v in LevelDBReader(path):
        w.put(k, v)
    w.close()
    r = LMDBReader(str(out))
    assert {r.key_at(i): r.value_at(i)
            for i in range(len(r))} == want
