"""Step-pipeline tests: device-side input prefetch, the bounded in-flight
dispatch window, async metric drain/NaN abort, and background snapshots.

The pipeline is numerics-NEUTRAL by construction — it moves host blocking,
never the dispatched step sequence — so the anchor test is bitwise parity
of the final parameters across ``max_in_flight`` in {1, 2, 4}, with device
prefetch + batch-buffer donation on (the default hot path) against the
fully serial loop (prefetch off, window 1).
"""

import glob
import os
import threading
import time

import numpy as np
import pytest

SMALLNET = """
name: "PipeNet"
layers {
  name: "mnist" type: MEMORY_DATA top: "data" top: "label"
  memory_data_param { batch_size: 8 channels: 1 height: 12 width: 12 }
}
layers {
  name: "conv1" type: CONVOLUTION bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 3
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "relu1" type: RELU bottom: "conv1" top: "conv1" }
layers { name: "pool1" type: POOLING bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layers {
  name: "ip1" type: INNER_PRODUCT bottom: "pool1" top: "ip1"
  inner_product_param { num_output: 5
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "loss" type: SOFTMAX_LOSS bottom: "ip1" bottom: "label"
  top: "loss" }
"""


def _solver(max_iter=30, **kw):
    from poseidon_tpu.proto.messages import (SolverParameter,
                                             load_net_from_string)
    return SolverParameter(train_net_param=load_net_from_string(SMALLNET),
                           base_lr=0.05, lr_policy="fixed", momentum=0.9,
                           weight_decay=5e-4, display=10, max_iter=max_iter,
                           random_seed=3, **kw)


def _memory_data(n=256, seed=0, poison=False):
    rs = np.random.RandomState(seed)
    templates = rs.randn(5, 1, 12, 12).astype(np.float32)
    labels = rs.randint(0, 5, size=n)
    data = templates[labels] + \
        0.25 * rs.randn(n, 1, 12, 12).astype(np.float32)
    if poison:
        data[:] = np.nan
    return {"data": data, "label": labels}


def _train_params(tmp_path, sub, **engine_kw):
    import jax
    from poseidon_tpu.runtime.engine import Engine

    out = tmp_path / sub
    out.mkdir()
    eng = Engine(_solver(), memory_data=_memory_data(),
                 output_dir=str(out), **engine_kw)
    try:
        last = eng.train()
        leaves = [np.asarray(v).copy()
                  for v in jax.tree_util.tree_leaves(eng.params)]
        eng._last_feed = eng._device_feed  # survives close() for asserts
        return last, leaves, eng
    finally:
        eng.close()


# --------------------------------------------------------------------------- #
# bitwise parity of the pipelined loop
# --------------------------------------------------------------------------- #

def test_max_in_flight_bitwise_parity(tmp_path, monkeypatch):
    """A fixed 30-iteration run produces bitwise-identical final params for
    max_in_flight in {1, 2, 4} with device prefetch on (the default hot
    path), all equal to the fully serial loop — both through the CPU
    passthrough prefetcher AND the real background-thread stage (forced
    on, the accelerator-backend path)."""
    from poseidon_tpu.data.pipeline import DevicePrefetcher

    last_s, serial, _ = _train_params(tmp_path, "serial",
                                      device_prefetch=0, max_in_flight=1)
    assert np.isfinite(last_s["loss"])
    for mif in (1, 2, 4):
        _, leaves, eng = _train_params(tmp_path, f"mif{mif}",
                                       device_prefetch=2, max_in_flight=mif)
        assert eng._use_prefetch  # the prefetch stage actually engaged
        for a, b in zip(serial, leaves):
            np.testing.assert_array_equal(a, b)
    # force the threaded stage (auto resolves to passthrough on CPU)
    monkeypatch.setattr(DevicePrefetcher, "_auto_passthrough",
                        staticmethod(lambda: False))
    _, leaves, eng = _train_params(tmp_path, "threaded",
                                   device_prefetch=2, max_in_flight=2)
    assert eng._last_feed is not None and not eng._last_feed.passthrough
    for a, b in zip(serial, leaves):
        np.testing.assert_array_equal(a, b)


def test_prefetch_disabled_for_stacked_paths(tmp_path):
    """iter_size > 1 and steps_per_dispatch > 1 assemble stacked host
    batches; the prefetcher must stand down (and training still run)."""
    from poseidon_tpu.runtime.engine import Engine

    sp = _solver(max_iter=8)
    sp.iter_size = 2
    eng = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path),
                 device_prefetch=2)
    try:
        assert not eng._use_prefetch
        assert np.isfinite(eng.train()["loss"])
    finally:
        eng.close()


# --------------------------------------------------------------------------- #
# NaN abort rides the async drain
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("mif", [1, 4])
def test_nan_abort_fires_within_window(tmp_path, mif):
    """A non-finite loss aborts the run within max_in_flight dispatches of
    the step that produced it, and the error rewinds to that step."""
    from poseidon_tpu.runtime.engine import Engine, TrainingDivergedError

    eng = Engine(_solver(), memory_data=_memory_data(poison=True),
                 output_dir=str(tmp_path), max_in_flight=mif)
    try:
        with pytest.raises(TrainingDivergedError) as exc:
            eng.train()
        # the poisoned data NaNs the very first step; the report rewinds
        # to it even though the loop may have dispatched further
        assert exc.value.iteration == 0
        assert exc.value.key == "loss"
        dispatched = eng.stats.counters["train_iters"]
        assert dispatched <= exc.value.iteration + 1 + mif
    finally:
        eng.close()


def test_fetcher_window_blocks_and_detects_divergence():
    """AsyncScalarFetcher unit: put() returns only when the window
    INCLUDING its own entry has room for the next dispatch (window 2:
    the first put returns with its entry pending, the second blocks until
    the first drains — so at most 2 dispatches are ever in flight), and
    the drain tags the diverged iteration."""
    from poseidon_tpu.runtime.metrics import AsyncScalarFetcher

    gate = threading.Event()

    class Blocked:
        """Scalar whose materialization (np.asarray) waits on ``gate`` —
        a stand-in for a device value whose step is still running (so
        ``is_ready`` is False until the gate opens and the inline
        fast path must NOT engage)."""

        def __init__(self, v):
            self.v = v

        def is_ready(self):
            return gate.is_set()

        def __array__(self, dtype=None):
            gate.wait(timeout=10.0)
            return np.asarray(self.v, dtype or np.float32)

    f = AsyncScalarFetcher(max_in_flight=2)
    try:
        t0 = time.monotonic()
        f.put(0, {"loss": Blocked(1.0)})  # drainer blocks materializing
        assert time.monotonic() - t0 < 5.0, \
            "window=2 must not block the first put"
        done = threading.Event()

        def second_put():
            f.put(1, {"loss": Blocked(float("nan"))})
            done.set()

        t = threading.Thread(target=second_put, daemon=True)
        t.start()
        time.sleep(0.2)
        assert not done.is_set(), "window=2 must block the second put"
        gate.set()
        t.join(timeout=10.0)
        assert done.is_set()
        rows = f.sync()
        assert [it for it, _ in rows] == [0, 1]
        assert f.divergence is not None and f.divergence[0] == 1
    finally:
        f.close()


def test_fetcher_window_one_is_serial():
    """max_in_flight=1 drains each entry before put() returns — no
    dispatch ever overlaps an unread metric (the serial loop)."""
    from poseidon_tpu.runtime.metrics import AsyncScalarFetcher

    f = AsyncScalarFetcher(max_in_flight=1)
    try:
        for i in range(3):
            f.put(i, {"loss": np.float32(i)})
            # the entry drained before put returned
            drained = f.take_drained()
            assert [it for it, _ in drained] == [i]
    finally:
        f.close()


def test_scalar_rows_expands_scan_chunks():
    from poseidon_tpu.runtime.metrics import scalar_rows

    rows = scalar_rows({"loss": np.asarray([1.0, 2.0, 3.0]),
                        "acc": np.asarray(0.5)})
    assert rows == [{"loss": 1.0, "acc": 0.5}, {"loss": 2.0, "acc": 0.5},
                    {"loss": 3.0, "acc": 0.5}]
    assert scalar_rows({"loss": np.asarray(4.0)}) == [{"loss": 4.0}]


# --------------------------------------------------------------------------- #
# async snapshots
# --------------------------------------------------------------------------- #

def test_async_snapshot_equals_sync_snapshot(tmp_path):
    """The async writer produces the identical artifacts: .caffemodel
    byte-for-byte, .solverstate arrays bitwise (the npz container embeds
    zip timestamps, so bytes are compared per-array)."""
    from poseidon_tpu.runtime.engine import Engine

    sp = _solver(max_iter=6, snapshot_prefix="snap/pipe",
                 snapshot_after_train=True)
    paths = {}
    for mode in ("sync", "async"):
        out = tmp_path / mode
        out.mkdir()
        eng = Engine(sp, memory_data=_memory_data(), output_dir=str(out),
                     async_snapshot=(mode == "async"))
        try:
            eng.train()
        finally:
            eng.close()
        paths[mode] = out / "snap" / "pipe_iter_6"
    with open(f"{paths['sync']}.caffemodel", "rb") as f:
        sync_model = f.read()
    with open(f"{paths['async']}.caffemodel", "rb") as f:
        async_model = f.read()
    assert sync_model == async_model
    a = np.load(f"{paths['sync']}.solverstate.npz")
    b = np.load(f"{paths['async']}.solverstate.npz")
    assert sorted(a.files) == sorted(b.files)
    for k in a.files:
        assert a[k].tobytes() == b[k].tobytes(), k


def test_async_snapshot_resumes_and_auto_resumes(tmp_path):
    """auto_resume semantics are untouched: a mid-train async snapshot is
    discoverable and restores to the right iteration."""
    from poseidon_tpu.runtime.engine import Engine

    sp = _solver(max_iter=20, snapshot=10, snapshot_prefix="snap/pipe")
    eng = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path),
                 async_snapshot=True)
    try:
        eng.train()
    finally:
        eng.close()
    # the mid-train cadence snapshot (iter 10) landed, and auto-resume
    # finds the newest one (the after-train iter-20 write)
    assert (tmp_path / "snap" / "pipe_iter_10.solverstate.npz").exists()
    eng2 = Engine(sp, memory_data=_memory_data(), output_dir=str(tmp_path),
                  async_snapshot=True)
    try:
        restored = eng2.auto_resume()
        assert restored and restored.endswith("pipe_iter_20.solverstate.npz")
        assert int(eng2.state.solver.it) == 20
    finally:
        eng2.close()


def test_torn_async_writer_shutdown_leaves_no_partial_files(tmp_path,
                                                            monkeypatch):
    """A writer that dies mid-write must leave at worst *.tmp.<pid> litter
    (collected by sweep_stale_tmp) — never a truncated real-suffix file —
    and the failure surfaces loudly on the next wait()."""
    import jax
    from poseidon_tpu.runtime import checkpoint as ckpt
    from poseidon_tpu.core.net import Net
    from poseidon_tpu.parallel import init_train_state
    from poseidon_tpu.proto.messages import load_net_from_string

    shapes = {"data": (8, 1, 12, 12), "label": (8,)}
    net = Net(load_net_from_string(SMALLNET), "TRAIN", source_shapes=shapes)
    params = net.init(jax.random.PRNGKey(0))
    state = init_train_state(params)
    prefix = str(tmp_path / "snap" / "torn")

    real_savez = np.savez

    def dying_savez(f, **arrays):
        f.write(b"partial bytes that must never land at the real name")
        raise IOError("disk vanished mid-write")

    monkeypatch.setattr(ckpt.np, "savez", dying_savez)
    w = ckpt.AsyncSnapshotWriter()
    w.submit(prefix, net, params, state)
    with pytest.raises(IOError):
        w.wait()
    # the torn write left only tmp litter; no real-suffix solverstate
    assert glob.glob(f"{prefix}*.solverstate.npz") == []
    litter = glob.glob(f"{prefix}*.tmp.*")
    assert litter, "the torn write should have left its tmp behind"
    removed = ckpt.sweep_stale_tmp(prefix, min_age_s=0.0)
    assert sorted(removed) == sorted(litter), "litter must be swept"
    # and the writer recovers: a healthy write lands both artifacts
    monkeypatch.setattr(ckpt.np, "savez", real_savez)
    w.submit(prefix, net, params, state)
    model, statef = w.wait()
    assert os.path.exists(model) and os.path.exists(statef)
    w.close()


def test_async_snapshot_failure_aborts_at_next_sync_boundary(tmp_path,
                                                             monkeypatch):
    """A failed BACKGROUND snapshot write must abort the run at the next
    sync boundary (the following snapshot cadence point, or end-of-train)
    with the writer's original error — never train to completion as if
    the snapshot existed, which would leave auto-resume pointing at
    nothing. Pinned for the elasticity story: preemptible fleets lean on
    snapshots + rejoin, so a silently-lost snapshot is a silently-lost
    worker contribution on the next restart."""
    from poseidon_tpu.runtime import checkpoint as ckpt
    from poseidon_tpu.runtime.engine import Engine

    def dying_savez(f, **arrays):
        raise IOError("disk vanished mid-write")

    monkeypatch.setattr(ckpt.np, "savez", dying_savez)
    sp = _solver(max_iter=30, snapshot=5, snapshot_prefix="snap/die")
    eng = Engine(sp, memory_data=_memory_data(),
                 output_dir=str(tmp_path), async_snapshot=True)
    try:
        with pytest.raises(IOError, match="disk vanished"):
            eng.train()
        # the abort landed at the NEXT snapshot boundary after the failed
        # iter-5 write (iter 10's submit joins the dead iter-5 thread) —
        # not at end-of-train 20 iterations later
        assert eng.iteration() <= 10, (
            f"failure surfaced only at iteration {eng.iteration()}; the "
            f"iter-10 sync boundary should have re-raised it")
    finally:
        eng.close()


# --------------------------------------------------------------------------- #
# device prefetcher: failure propagation + fault-injection interop
# --------------------------------------------------------------------------- #

def test_device_prefetcher_propagates_source_failure():
    """A dying pipeline worker surfaces on __next__ instead of wedging."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    from poseidon_tpu.data.pipeline import DevicePrefetcher
    from poseidon_tpu.parallel import make_mesh

    class DyingPipe:
        def __init__(self):
            self.n = 0

        def __next__(self):
            self.n += 1
            if self.n > 2:
                raise IOError("record store vanished")
            return {"data": np.zeros((8, 4), np.float32)}

    sharding = NamedSharding(make_mesh(), P("data"))
    # passthrough=False forces the background thread (the accelerator
    # path; auto resolves to passthrough on the CPU suite backend)
    for passthrough in (False, True):
        feed = DevicePrefetcher([DyingPipe()], sharding, depth=2,
                                passthrough=passthrough)
        try:
            seen = 0
            with pytest.raises(IOError, match="vanished"):
                for _ in range(4):
                    np.asarray(next(feed)["data"])
                    seen += 1
            assert seen == 2
            # the death is sticky: a retried dequeue re-raises immediately
            # instead of blocking forever on a dead worker's empty queue
            with pytest.raises(IOError, match="vanished"):
                next(feed)
        finally:
            feed.close()


def test_nan_is_never_snapshotted(tmp_path):
    """A snapshot boundary is a hard sync point: params poisoned by a NaN
    the drainer has not yet surfaced must never be persisted (and then
    silently auto-resumed) — the divergence aborts BEFORE the write."""
    from poseidon_tpu.runtime.engine import Engine, TrainingDivergedError

    sp = _solver(max_iter=30, snapshot=2, snapshot_prefix="snap/poison")
    eng = Engine(sp, memory_data=_memory_data(poison=True),
                 output_dir=str(tmp_path), max_in_flight=4)
    try:
        with pytest.raises(TrainingDivergedError):
            eng.train()
    finally:
        eng.close()
    assert glob.glob(str(tmp_path / "snap" / "*.solverstate.npz")) == []
    assert glob.glob(str(tmp_path / "snap" / "*.caffemodel")) == []


def test_device_prefetch_faultproxy_async_tier_interop(tmp_path,
                                                       monkeypatch):
    """Device prefetch composes with the fault-injection harness: an
    async-SSP worker whose ONLY cross-process channel rides a FaultProxy
    delay rule (slow != dead) trains to completion with the prefetcher
    feeding device-resident batches, and its clocks land on the service."""
    import jax
    from poseidon_tpu.parallel.async_ssp import ParamService
    from poseidon_tpu.runtime.engine import Engine
    from poseidon_tpu.runtime.faults import FaultProxy, FaultRule

    # seed the service with the engine's exact param tree structure
    probe = Engine(_solver(max_iter=1), memory_data=_memory_data(),
                   output_dir=str(tmp_path))
    host = {l: {p: np.asarray(v, np.float32) for p, v in ps.items()}
            for l, ps in probe.params.items()}
    probe.close()

    svc = ParamService(host, n_workers=2, liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    proxy.add_rule(FaultRule(action="delay", delay_s=0.005))
    monkeypatch.setenv("POSEIDON_PROC_ID", "1")
    monkeypatch.setenv("POSEIDON_NUM_PROCS", "2")
    monkeypatch.delenv("POSEIDON_COORDINATOR", raising=False)
    try:
        eng = Engine(_solver(max_iter=6), memory_data=_memory_data(),
                     output_dir=str(tmp_path), device_prefetch=2,
                     max_in_flight=2,
                     async_ssp={"staleness": 8, "sync_every": 1,
                                "service_port": proxy.port})
        try:
            last = eng.train()
            assert np.isfinite(last["loss"])
            assert eng._use_prefetch
        finally:
            eng.close()
        assert svc.clocks[1] >= 5, svc.clocks
    finally:
        proxy.close()
        svc.close()
