"""SPMD sharding planner (parallel/spmd.py): mesh, plan, parity, census.

The acceptance pins (ISSUE 10 / ROADMAP item 1):
- make_mesh fails loudly (no silent truncation; balanced multi-axis
  default);
- every DENSE leaf gets a placement, the fsdp shard ranges cover the
  padded arena disjointly, and SFB/TOPK layers opt out of tp;
- LeNet under dp2,fsdp2 is BITWISE identical to the replicated control
  on the same mesh (the hierarchical reduce-scatter -> all-reduce order
  matches the control's psum -> psum association exactly); dp2,tp2
  agrees to float-associativity tolerance (a sharded contraction
  re-associates its reduction);
- the sharded-state (ZeRO) layout computes the same numbers with 1/fsdp
  persistent arena bytes per device;
- the lowered collective census equals the planned schedule (the same
  comparison the checked-in HLO contracts gate in CI);
- snapshots stay canonical per-leaf: a dp2,fsdp2 run's snapshot restores
  bit-identically into a replicated run.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from poseidon_tpu.config import MeshConfig
from poseidon_tpu.core.net import Net
from poseidon_tpu.models import zoo
from poseidon_tpu.parallel import (CommConfig, build_ssp_train_step,
                                   init_ssp_state, init_train_state,
                                   make_mesh)
from poseidon_tpu.parallel.mesh import balanced_shape
from poseidon_tpu.parallel.spmd import (COL, ROW, ShardingPlan,
                                        build_spmd_train_step,
                                        fsdp_shard_ranges, named_mesh,
                                        shard_train_state,
                                        sharded_state_avals,
                                        unshard_train_state)
from poseidon_tpu.parallel.strategies import SFB, TOPK
from poseidon_tpu.proto.messages import SolverParameter
from poseidon_tpu.runtime.hlo_comm import collective_census_stablehlo

pytestmark = pytest.mark.mesh

N_DEV = 8
BATCH = 16


@pytest.fixture(scope="module", autouse=True)
def _release_compiled_steps():
    """This module compiles ~a dozen distinct SPMD step variants; drop
    them from jax's global caches at module teardown so the rest of the
    tier-1 sweep doesn't carry their executables as resident ballast."""
    yield
    jax.clear_caches()

SP = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                     weight_decay=0.0005)


def _lenet(n_dp):
    return Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
               source_shapes=zoo.lenet_shapes(BATCH // n_dp))


def _batch(rng):
    return {
        "data": jnp.asarray(rng.randn(BATCH, 1, 28, 28).astype(np.float32)),
        "label": jnp.asarray(rng.randint(0, 10, size=(BATCH,))),
    }


def _tree_equal(a, b, what=""):
    assert set(a) == set(b)
    for l in a:
        for k in a[l]:
            np.testing.assert_array_equal(
                np.asarray(a[l][k]), np.asarray(b[l][k]),
                err_msg=f"{what} {l}/{k}")


def _run(net, mesh, plan, comm, params, batch, rng, n_steps=3):
    ts = build_spmd_train_step(net, SP, mesh, plan, comm, donate=False)
    p, s = params, init_train_state(params, comm, plan.n_dp)
    for i in range(n_steps):
        p, s, m = ts.step(p, s, batch, jax.random.fold_in(rng, i))
    return ts, p, s, m


# --------------------------------------------------------------------------- #
# make_mesh footguns (satellite: no silent truncation, balanced default)
# --------------------------------------------------------------------------- #

def test_make_mesh_rejects_too_many_devices():
    assert jax.device_count() == N_DEV
    with pytest.raises(ValueError, match="only 8 exist"):
        make_mesh(num_devices=16)
    with pytest.raises(ValueError, match="must be positive"):
        make_mesh(num_devices=0)


def test_make_mesh_balanced_multi_axis_default():
    m = make_mesh(axes=("a", "b"))
    assert tuple(m.shape.values()) == (4, 2)       # not the old (8, 1)
    m3 = make_mesh(axes=("a", "b", "c"))
    assert tuple(m3.shape.values()) == (2, 2, 2)
    assert balanced_shape(12, 2) == (4, 3)
    assert balanced_shape(7, 2) == (7, 1)


def test_make_mesh_shape_mismatch_is_loud():
    with pytest.raises(ValueError, match="needs 6 devices, have 8"):
        make_mesh(axes=("a", "b"), shape=(3, 2))
    with pytest.raises(ValueError, match="2 dims for 1 axes"):
        make_mesh(axes=("a",), shape=(4, 2))


def test_mesh_config_parse():
    cfg = MeshConfig.parse("dp2,fsdp2,tp1")
    assert (cfg.data, cfg.fsdp, cfg.tp) == (2, 2, 1)
    assert cfg.n_devices == 4 and cfg.active and cfg.shard
    assert not MeshConfig.parse("dp4").active
    assert not MeshConfig.parse("dp2,fsdp2,replicated").shard
    with pytest.raises(ValueError, match="cannot parse"):
        MeshConfig.parse("dp2,zz3")
    with pytest.raises(ValueError, match="given twice"):
        MeshConfig.parse("dp2,dp4")


# --------------------------------------------------------------------------- #
# planner unit contracts
# --------------------------------------------------------------------------- #

def test_every_dense_leaf_gets_a_placement():
    net = _lenet(4)
    plan = ShardingPlan.build(net, MeshConfig(data=2, fsdp=2, tp=1),
                              CommConfig())
    for lname, defs in net.param_defs.items():
        for pdef in defs:
            assert (lname, pdef.name) in plan.leaf_plan, (lname, pdef.name)
            assert plan.leaf_plan[(lname, pdef.name)].placement == \
                "arena_fsdp"


def test_planner_megatron_pairing_on_lenet():
    """ip1 -> relu1 (in-place) -> ip2 becomes the COL(sharded-out) -> ROW
    pair with the resharding point at the ROW psum."""
    net = _lenet(4)
    plan = ShardingPlan.build(net, MeshConfig(data=2, fsdp=1, tp=2),
                              CommConfig())
    assert plan.tp_layers["ip1"].mode == COL
    assert not plan.tp_layers["ip1"].gather
    assert plan.tp_layers["ip2"].mode == ROW
    assert "ip1" in plan.sharded_blobs
    assert plan.leaf_plan[("ip1", "w")].spec == \
        jax.sharding.PartitionSpec("tp", None)
    assert plan.leaf_plan[("ip2", "w")].spec == \
        jax.sharding.PartitionSpec(None, "tp")


def test_tp_opt_out_for_sfb_topk_layers():
    net = _lenet(4)
    comm = CommConfig(layer_strategies={"ip1": SFB, "ip2": TOPK})
    plan = ShardingPlan.build(net, MeshConfig(data=2, fsdp=1, tp=2), comm)
    assert plan.tp_layers == {}
    for lname in ("ip1", "ip2"):
        for pdef in net.param_defs[lname]:
            lp = plan.leaf_plan[(lname, pdef.name)]
            assert lp.placement == "replicated"
            assert lp.spec == jax.sharding.PartitionSpec()


def test_fsdp_shard_ranges_cover_disjointly():
    net = _lenet(4)
    for f, bucket_mb in ((2, 0.05), (4, 0.3), (8, 4.0)):
        layout = net.arena_layout(bucket_mb=bucket_mb, align=f)
        ranges = fsdp_shard_ranges(layout, f)
        assert len(ranges) == f
        seen = np.zeros(layout.padded_total, np.int32)
        for dev_ranges in ranges:
            assert len(dev_ranges) == layout.n_buckets
            for lo, hi in dev_ranges:
                seen[lo:hi] += 1
        assert (seen == 1).all()        # disjoint cover, no gaps
        assert layout.padded_total % f == 0


def test_fsdp_without_arena_is_rejected():
    net = _lenet(4)
    with pytest.raises(ValueError, match="rides the flat parameter arena"):
        ShardingPlan.build(net, MeshConfig(data=2, fsdp=2, tp=1),
                           CommConfig(param_arena=False))


# --------------------------------------------------------------------------- #
# parity: sharded vs replicated control on the SAME mesh
# --------------------------------------------------------------------------- #

def test_lenet_fsdp_bitwise_parity(rng_np):
    """dp2,fsdp2 sharded arm == replicated arm, bitwise, params AND
    momentum, across 3 steps — reduce-scatter + shard-psum reduces in the
    same association order as the control's hierarchical psums."""
    cfg = MeshConfig.parse("dp2,fsdp2")
    mesh = named_mesh(cfg)
    net = _lenet(4)
    comm = CommConfig()
    params = net.init(jax.random.PRNGKey(0))
    batch, rng = _batch(rng_np), jax.random.PRNGKey(7)
    _, p1, s1, m1 = _run(net, mesh,
                         ShardingPlan.build(net, cfg, comm),
                         comm, params, batch, rng)
    _, p2, s2, m2 = _run(net, mesh,
                         ShardingPlan.build(net, cfg, comm,
                                            shard_params=False),
                         comm, params, batch, rng)
    assert float(m1["loss"]) == float(m2["loss"])
    _tree_equal(p1, p2, "params")
    _tree_equal(s1.solver.history, s2.solver.history, "history")


def test_lenet_tp_parity(rng_np):
    """dp2,tp2 (COL ip1 -> ROW ip2) vs the tp-off control on the same
    mesh: loss and params agree to float-associativity tolerance — the
    sharded contraction necessarily re-associates its K/M reductions, so
    bitwise is not achievable (unlike fsdp)."""
    cfg = MeshConfig.parse("dp2,tp2")
    mesh = named_mesh(cfg)
    net = _lenet(2)
    comm = CommConfig()
    params = net.init(jax.random.PRNGKey(0))
    batch, rng = _batch(rng_np), jax.random.PRNGKey(7)
    plan_tp = ShardingPlan.build(net, cfg, comm)
    assert plan_tp.tp_layers            # the pairing actually engaged
    _, p1, _, m1 = _run(net, mesh, plan_tp, comm, params, batch, rng)
    _, p2, _, m2 = _run(net, mesh,
                        ShardingPlan.build(net, cfg, comm,
                                           enable_tp=False),
                        comm, params, batch, rng)
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]),
                               rtol=1e-6)
    for l in p1:
        for k in p1[l]:
            np.testing.assert_allclose(
                np.asarray(p1[l][k]), np.asarray(p2[l][k]),
                rtol=1e-5, atol=1e-7, err_msg=f"{l}/{k}")


def test_sharded_state_matches_canonical_bitwise(rng_np):
    """The ZeRO layout (params+momentum living 1/fsdp per device, param
    all-gather in the prologue) computes the canonical step's numbers
    bitwise, and each device's persistent arena shard is exactly
    padded_total/fsdp elements."""
    cfg = MeshConfig.parse("dp2,fsdp2")
    mesh = named_mesh(cfg)
    net = _lenet(4)
    comm = CommConfig()
    params = net.init(jax.random.PRNGKey(0))
    batch, rng = _batch(rng_np), jax.random.PRNGKey(7)
    plan = ShardingPlan.build(net, cfg, comm)
    ts, p1, s1, m1 = _run(net, mesh, plan, comm, params, batch, rng)

    ts2 = build_spmd_train_step(net, SP, mesh, plan, comm, donate=False,
                                sharded_state=True)
    st = shard_train_state(params, init_train_state(params, comm, 4),
                           ts2.arena, mesh, plan)
    for sh in st.flat_w.addressable_shards:
        assert sh.data.shape == (ts2.arena.padded_total // 2,)
    for i in range(3):
        st, m2 = ts2.step(st, batch, jax.random.fold_in(rng, i))
    p2, s2 = unshard_train_state(st, ts2.arena, plan)
    assert float(m1["loss"]) == float(m2["loss"])
    _tree_equal(p1, p2, "params")
    _tree_equal(s1.solver.history, s2.solver.history, "history")


def test_sharded_state_avals_lower(rng_np):
    """AOT entry (scripts/aot_tpu_check.py --sections mesh): lowering the
    sharded-state step from ShapeDtypeStruct avals works, and the
    program's per-device argument footprint carries the 1/fsdp arena."""
    cfg = MeshConfig.parse("dp2,fsdp2")
    mesh = named_mesh(cfg)
    net = _lenet(4)
    comm = CommConfig()
    plan = ShardingPlan.build(net, cfg, comm)
    ts = build_spmd_train_step(net, SP, mesh, plan, comm, donate=False,
                               sharded_state=True)
    st = sharded_state_avals(net, ts.arena, plan, mesh)
    from jax.sharding import NamedSharding, PartitionSpec as P
    bspec = NamedSharding(mesh, P(("data", "fsdp")))
    batch = {"data": jax.ShapeDtypeStruct((BATCH, 1, 28, 28), jnp.float32,
                                          sharding=bspec),
             "label": jax.ShapeDtypeStruct((BATCH,), jnp.int32,
                                           sharding=bspec)}
    rng = jax.ShapeDtypeStruct((2,), jnp.uint32,
                               sharding=NamedSharding(mesh, P()))
    txt = ts.lowerable.lower(st, batch, rng).as_text()
    census = collective_census_stablehlo(txt)
    sched = plan.collective_schedule(ts.arena, net, sharded_state=True)
    assert census == sched["counts"]


# --------------------------------------------------------------------------- #
# collective census == planned schedule (the contract gate's comparison)
# --------------------------------------------------------------------------- #

@pytest.mark.parametrize("spec,comm_kw", [
    ("dp2,fsdp2", {}),
    ("dp2,tp2", {}),
    ("dp2,fsdp2,tp2", {}),
    # non-default strategies must be stated too (TOPK compressed psum,
    # SFB factor gathers, arena-off in-backward taps)
    ("dp2,tp2", {"layer_strategies": {"ip2": TOPK}}),
    ("dp2,fsdp2", {"layer_strategies": {"ip1": SFB}}),
    ("dp2,tp2", {"param_arena": False}),
])
def test_collective_census_matches_plan(rng_np, spec, comm_kw):
    cfg = MeshConfig.parse(spec)
    mesh = named_mesh(cfg)
    net = _lenet(cfg.data * cfg.fsdp)
    comm = CommConfig(**comm_kw)
    params = net.init(jax.random.PRNGKey(0))
    plan = ShardingPlan.build(net, cfg, comm)
    ts = build_spmd_train_step(net, SP, mesh, plan, comm, donate=False)
    state = init_train_state(params, comm, plan.n_dp)
    txt = ts.lowerable.lower(params, state, _batch(rng_np),
                             jax.random.PRNGKey(1)).as_text()
    census = collective_census_stablehlo(txt)
    sched = plan.collective_schedule(ts.arena, net, comm=comm)
    assert census == sched["counts"], (census, sched["counts"])
    if cfg.fsdp > 1 and not comm_kw:
        assert sched["counts"]["reduce_scatter"] == ts.arena.n_buckets


def test_size_mismatch_without_tp_plan_is_loud():
    """A wrong-size leaf on a run with no tp plan covering it must fail
    at param resolution, not silently broadcast (the tp-shard escape
    hatch is plan-gated)."""
    net = _lenet(N_DEV)
    params = net.init(jax.random.PRNGKey(0))
    params["ip1"]["b"] = jnp.zeros((1,), jnp.float32)   # wrong size
    x = {"data": jnp.zeros((2, 1, 28, 28)), "label": jnp.zeros((2,),
                                                               jnp.int32)}
    with pytest.raises(ValueError, match="no tensor-parallel plan"):
        net.apply(params, x, train=False)


# --------------------------------------------------------------------------- #
# snapshot portability: canonical per-leaf across meshes
# --------------------------------------------------------------------------- #

def test_snapshot_portable_to_replicated_run(rng_np, tmp_path):
    """A dp2,fsdp2 run's snapshot restores bit-identically (canonical
    per-leaf trees), and a flat replicated data-parallel step consumes
    the restored state directly — cross-mesh portability."""
    from poseidon_tpu.parallel import build_train_step
    from poseidon_tpu.runtime.checkpoint import restore, snapshot

    cfg = MeshConfig.parse("dp2,fsdp2")
    mesh = named_mesh(cfg)
    net = _lenet(4)
    comm = CommConfig()
    params = net.init(jax.random.PRNGKey(0))
    batch, rng = _batch(rng_np), jax.random.PRNGKey(7)
    plan = ShardingPlan.build(net, cfg, comm)
    _, p1, s1, _ = _run(net, mesh, plan, comm, params, batch, rng,
                        n_steps=2)
    prefix = str(tmp_path / "lenet")
    _, statef = snapshot(prefix, net, p1, s1)
    rparams, rstate = restore(statef)
    _tree_equal(p1, rparams, "restored params")
    _tree_equal(s1.solver.history, rstate.solver.history, "restored hist")
    assert int(rstate.solver.it) == 2

    # restored state drives a REPLICATED flat-mesh run (different net
    # instance, different mesh) without conversion
    flat_mesh = make_mesh()
    net2 = _lenet(N_DEV)
    ts2 = build_train_step(net2, SP, flat_mesh, comm, donate=False)
    p2, s2, m2 = ts2.step(rparams, rstate, batch,
                          jax.random.fold_in(rng, 2))
    assert np.isfinite(float(m2["loss"]))


# --------------------------------------------------------------------------- #
# engine / CLI acceptance arm
# --------------------------------------------------------------------------- #

def test_engine_mesh_cli_bitwise_vs_replicated(tmp_path):
    """The acceptance criterion end to end: an Engine run under
    ``--mesh dp2,fsdp2`` produces final params bitwise equal to the
    ``--mesh dp2,fsdp2,replicated`` control run."""
    import sys
    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    from test_runtime import _memory_data, _write_mnistish_prototxt
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    sp = load_solver(_write_mnistish_prototxt(tmp_path, max_iter=8))
    sp.test_interval = 0
    finals = {}
    for spec in ("dp2,fsdp2", "dp2,fsdp2,replicated"):
        eng = Engine(sp, mesh_cfg=MeshConfig.parse(spec),
                     memory_data=_memory_data(),
                     output_dir=str(tmp_path / spec.replace(",", "_")))
        try:
            eng.train()
            finals[spec] = {l: {k: np.asarray(v)
                                for k, v in lp.items()}
                            for l, lp in eng.params.items()}
            assert eng.plan is not None
            assert eng.plan.shard_params == (spec == "dp2,fsdp2")
        finally:
            eng.close()
    _tree_equal(finals["dp2,fsdp2"], finals["dp2,fsdp2,replicated"],
                "engine")


# --------------------------------------------------------------------------- #
# SSP tier on the named mesh
# --------------------------------------------------------------------------- #

def test_ssp_fsdp_delta_exchange(rng_np):
    """SSP staleness on a dp2,fsdp2 mesh: the boundary arena delta
    exchange reshards over fsdp (reduce-scatter / all-gather in the
    lowered program) and the run converges like the flat-mesh tier."""
    cfg = MeshConfig.parse("dp2,fsdp2")
    mesh = named_mesh(cfg)
    net = _lenet(4)
    comm = CommConfig()
    plan = ShardingPlan.build(net, cfg, comm)
    params = net.init(jax.random.PRNGKey(0))
    ts = build_ssp_train_step(net, SP, mesh, 1, comm, plan=plan)
    txt = ts.lowerable.lower(
        init_ssp_state(params, plan.n_dp, comm), _batch(rng_np),
        jax.random.PRNGKey(0)).as_text()
    census = collective_census_stablehlo(txt)
    assert census["reduce_scatter"] >= 1
    assert census["all_gather"] >= 1
    st = init_ssp_state(params, plan.n_dp, comm)
    b = _batch(rng_np)
    losses = []
    for i in range(6):
        st, m = ts.step(st, b, jax.random.PRNGKey(i))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0]


def test_comm_scopes_attribute_per_axis():
    """The spmd collective scopes are recognized as named attribution
    rows (never residual) and map to their mesh axis — the per-axis comm
    rows `bench.py attribution` aggregates into comm_ms_by_axis."""
    from poseidon_tpu.runtime import attribution as A
    layers = {"conv1", "ip1"}
    for scope, axis in (("grad_rs_bucket0", "fsdp"),
                        ("grad_ar_bucket3", "data"),
                        ("param_ag_bucket1", "fsdp"),
                        ("hist_ag_bucket0", "fsdp"),
                        ("grad_sync_bucket2", "data"),
                        ("delta_rs_bucket0", "fsdp"),
                        ("tp_fwd_ip1", "tp"),
                        ("tp_dx_ip1", "tp"),
                        ("grad_tp_ip1_w_fsdp", "fsdp"),
                        ("grad_tp_ip1_w_data", "data")):
        got = A.scope_of(f"jit(step)/{scope}/psum", layers)
        assert got == (scope, "misc"), (scope, got)
        assert A.comm_axis_of(scope) == axis, scope
    # layer scopes still win over comm detection, and unknowns stay None
    assert A.scope_of("jit(step)/jvp(ip1)/dot", layers) == ("ip1", "fwd")
    assert A.comm_axis_of("optimizer_update") is None


def test_ssp_rejects_tp():
    cfg = MeshConfig.parse("dp2,tp2")
    mesh = named_mesh(cfg)
    net = _lenet(2)
    plan = ShardingPlan.build(net, cfg, CommConfig())
    with pytest.raises(ValueError, match="tensor parallelism"):
        build_ssp_train_step(net, SP, mesh, 1, CommConfig(), plan=plan)
