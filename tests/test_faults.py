"""Fault tolerance for the async-SSP process tier (ISSUE 1).

The reference is fail-fast: any connection error aborts the whole job
(comm_bus.hpp:22-24) and the SSP read gate blocks until EVERY worker's
clock advances — one preempted process wedges the cluster. These tests pin
the elastic semantics that replace it: liveness eviction (survivors'
gates unblock), exactly-once PUSH replay across reconnects, rejoin, and
clean surfacing of permanent failure — all exercised deterministically
through the :mod:`poseidon_tpu.runtime.faults` loopback proxy
(drop/delay/truncate/sever rules on exact byte counts and connection
indices, nothing random).

Every socket here binds port 0 on loopback — no fixed ports, no flakes.
Tests that sleep more than ~5 s carry ``@pytest.mark.slow``.
"""

import pickle
import socket
import struct
import threading
import time

import numpy as np
import pytest

from poseidon_tpu.parallel.async_ssp import (WIRE_CODEC_VERSION,
                                             AsyncSSPClient, ParamService,
                                             _recv_msg, _send_msg,
                                             run_async_ssp_worker)
from poseidon_tpu.runtime.faults import FaultProxy, FaultRule
from poseidon_tpu.runtime.retry import retry_with_backoff

# tight knobs so every reconnect/eviction resolves in test time
FAST = dict(heartbeat_s=0.1, reconnect_deadline_s=5.0,
            backoff_base_s=0.01, backoff_cap_s=0.1)


def _zeros_params(shape=(2, 2)):
    return {"fc": {"w": np.zeros(shape, np.float32)}}


def _one(shape=(2, 2)):
    return {"fc": {"w": np.ones(shape, np.float32)}}


def _counting_step(worker):
    def step(params, it):
        out = {l: {p: v + 1.0 for p, v in ps.items()}
               for l, ps in params.items()}
        return out, 0.0
    return step


def _wait_for(pred, timeout_s=10.0, what="condition"):
    deadline = time.time() + timeout_s
    while not pred():
        if time.time() > deadline:
            raise AssertionError(f"timed out waiting for {what}")
        time.sleep(0.01)


# --------------------------------------------------------------------------- #
# retry helper
# --------------------------------------------------------------------------- #

def test_retry_with_backoff_policy():
    """Succeeds after transient failures; re-raises the LAST retryable
    error on deadline exhaustion; non-retryable errors propagate
    immediately (no sleep, no swallow)."""
    import random
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("not yet")
        return 42

    assert retry_with_backoff(flaky, deadline=5.0, base=0.001, cap=0.01,
                              rng=random.Random(0)) == 42
    assert len(calls) == 3

    def always() -> None:
        raise ConnectionRefusedError("down")

    t0 = time.monotonic()
    with pytest.raises(ConnectionRefusedError):
        retry_with_backoff(always, deadline=0.2, base=0.01, cap=0.05)
    assert time.monotonic() - t0 < 2.0

    def bug() -> None:
        raise ValueError("not transient")

    t0 = time.monotonic()
    with pytest.raises(ValueError):
        retry_with_backoff(bug, deadline=5.0)
    assert time.monotonic() - t0 < 1.0


# --------------------------------------------------------------------------- #
# service-side liveness / exactly-once / frame containment
# --------------------------------------------------------------------------- #

def test_gate_unblocks_after_liveness_eviction():
    """The acceptance property: a worker that hangs (socket open, no
    traffic) is evicted at the liveness timeout and the survivor's gate
    unblocks — where the reference would hang until the 120 s backstop."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=2, liveness_timeout_s=0.4)
    hung = socket.create_connection(("127.0.0.1", svc.port))
    try:
        _send_msg(hung, {"kind": "hello", "worker": 1})
        _recv_msg(hung)
        cli = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=0,
                             n_workers=2, **FAST)
        try:
            cli.push(_one())
            # s=0: gate(1) needs worker 1 at clock >= 0; it is hung at -1
            waited = cli.gate(1, timeout_s=30.0)
            assert 0.1 < waited < 10.0, waited
            assert 1 in cli.failed
            assert 1 in svc.failed_workers
            assert svc.evictions == 1
        finally:
            cli.close()
    finally:
        hung.close()
        svc.close()


def test_duplicate_push_applied_once():
    """A replayed flush whose ack was lost must not double-apply: the
    service dedups on the per-worker sequence number and acks the
    duplicate without touching the anchor."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=1, liveness_timeout_s=0.0)
    sk = socket.create_connection(("127.0.0.1", svc.port))
    try:
        _send_msg(sk, {"kind": "hello", "worker": 0})
        _recv_msg(sk)
        msg = {"kind": "push", "worker": 0, "clock": 0, "seq": 0,
               "delta": _one()}
        _send_msg(sk, msg)
        ack1 = _recv_msg(sk)
        _send_msg(sk, msg)          # the retry after a lost ack
        ack2 = _recv_msg(sk)
        assert ack1["dup"] is False
        assert ack2["dup"] is True
        np.testing.assert_allclose(svc.anchor["fc"]["w"], 1.0)
        assert svc.applied_seq[0] == 0
        assert svc.clocks[0] == 0
    finally:
        sk.close()
        svc.close()


def test_malformed_frames_do_not_kill_service():
    """A torn header, a mid-message EOF, and an undecodable payload each
    cost one connection and one logged counter — never the service: a
    well-behaved client keeps training through all three."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=1, liveness_timeout_s=0.0)
    try:
        # mid-message EOF: header promises 50 bytes, peer sends 10 and dies
        bad = socket.create_connection(("127.0.0.1", svc.port))
        bad.sendall(struct.pack("!Q", 50) + b"0123456789")
        bad.close()
        # undecodable payload: complete frame, garbage bytes
        bad2 = socket.create_connection(("127.0.0.1", svc.port))
        bad2.sendall(struct.pack("!Q", 4) + b"\x00\x01\x02\x03")
        bad2.close()
        # absurd length header (a stray HTTP probe, say)
        bad3 = socket.create_connection(("127.0.0.1", svc.port))
        bad3.sendall(b"GET / HT")
        bad3.close()
        _wait_for(lambda: svc.bad_frames >= 3, what="bad_frames >= 3")

        cli = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=0,
                             n_workers=1, **FAST)
        try:
            cli.push(_one())
            cli._drain()
            np.testing.assert_allclose(svc.anchor["fc"]["w"], 1.0)
        finally:
            cli.close()
    finally:
        svc.close()


def test_bad_request_shape_is_contained():
    """A structurally-valid pickle with an unknown kind drops only its
    own connection (logged), not the per-connection thread's stack into
    the service."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=1, liveness_timeout_s=0.0)
    sk = socket.create_connection(("127.0.0.1", svc.port))
    try:
        _send_msg(sk, {"kind": "no-such-rpc", "worker": 0})
        _wait_for(lambda: svc.bad_frames >= 1, what="bad request counted")
        cli = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=0,
                             n_workers=1, **FAST)
        try:
            cli.push(_one())
            cli._drain()
            np.testing.assert_allclose(svc.anchor["fc"]["w"], 1.0)
        finally:
            cli.close()
    finally:
        sk.close()
        svc.close()


# --------------------------------------------------------------------------- #
# fault-proxy scenarios (drop / truncate / sever / delay / partition)
# --------------------------------------------------------------------------- #

def test_proxy_drop_rule_exercises_connect_backoff():
    """drop: the first two dial attempts see accept-then-close; the
    client's backoff loop redials and lands the third — training output
    identical to a clean run."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=1, liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    proxy.add_rule(FaultRule(action="drop", max_conns=2))
    try:
        cli = AsyncSSPClient(0, proxy.addr, staleness=0, n_workers=1,
                             retry_s=10.0, **FAST)
        try:
            cli.push(_one())
            cli._drain()
            np.testing.assert_allclose(svc.anchor["fc"]["w"], 1.0)
            assert proxy.dropped == 2
        finally:
            cli.close()
    finally:
        proxy.close()
        svc.close()


def test_proxy_truncated_frame_is_replayed_exactly_once():
    """truncate: the push channel is cut 12 bytes into the first PUSH
    frame. The service contains the torn frame (FrameError, logged, no
    crash); the client reconnects and replays; the seq dedup guarantees
    the anchor gets the increment exactly once."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=1, liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    hello = pickle.dumps({"kind": "hello", "worker": 0},
                         protocol=pickle.HIGHEST_PROTOCOL)
    wire_neg = pickle.dumps({"kind": "wire", "codec": WIRE_CODEC_VERSION},
                            protocol=pickle.HIGHEST_PROTOCOL)
    # budget: the whole hello + codec-negotiation frames + 12 bytes —
    # deterministically inside the first push frame (conn 0 is the push
    # channel: it dials first)
    proxy.add_rule(FaultRule(action="truncate", conn=0,
                             after_bytes=len(hello) + 8
                             + len(wire_neg) + 8 + 12))
    try:
        cli = AsyncSSPClient(0, proxy.addr, staleness=0, n_workers=1,
                             **FAST)
        try:
            cli.push(_one())
            cli._drain(timeout_s=10.0)
            np.testing.assert_allclose(svc.anchor["fc"]["w"], 1.0)
            assert svc.applied_seq[0] == 0
            assert svc.bad_frames >= 1      # the torn frame was seen+logged
            assert cli.reconnects >= 1
        finally:
            cli.close()
    finally:
        proxy.close()
        svc.close()


def test_reconnect_after_sever_resumes_correct_values():
    """sever_all: a hard mid-run partition of every live connection. Both
    channels redial through the proxy; the un-acked flush replays; pull
    traffic resumes; parameter values are exactly a clean run's."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=1, liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    try:
        cli = AsyncSSPClient(0, proxy.addr, staleness=0, n_workers=1,
                             **FAST)
        try:
            cli.push(_one())
            cli._drain()
            assert proxy.sever_all() >= 1
            cli.push(_one())            # hits the dead socket -> reconnect
            cli._drain(timeout_s=10.0)
            np.testing.assert_allclose(svc.anchor["fc"]["w"], 2.0)
            assert svc.applied_seq[0] == 1
            assert cli.reconnects >= 1
            cache, clocks = cli.refresh()   # pull channel recovers too
            np.testing.assert_allclose(cache["fc"]["w"], 2.0)
            assert clocks[0] == 1
        finally:
            cli.close()
    finally:
        proxy.close()
        svc.close()


def test_proxy_delay_slow_is_not_dead():
    """delay: a congested path adds latency to every chunk; heartbeats
    still flow, so the liveness monitor must NOT evict the slow-but-alive
    worker (slow != dead)."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=1, liveness_timeout_s=0.8)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    proxy.add_rule(FaultRule(action="delay", delay_s=0.05))
    try:
        cli = AsyncSSPClient(0, proxy.addr, staleness=0, n_workers=1,
                             **FAST)
        try:
            for _ in range(3):
                cli.push(_one())
            cli._drain(timeout_s=10.0)
            time.sleep(1.2)             # > liveness timeout of idle silence
            assert 0 not in svc.failed_workers
            assert svc.evictions == 0
            np.testing.assert_allclose(svc.anchor["fc"]["w"], 3.0)
        finally:
            cli.close()
    finally:
        proxy.close()
        svc.close()


def test_permanent_failure_surfaces_to_training_loop():
    """When the partition outlives the reconnect deadline the failure
    must reach the TRAINING LOOP as an exception — never a silently dead
    sender thread quietly dropping oplogs."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=1, liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    try:
        cli = AsyncSSPClient(0, proxy.addr, staleness=0, n_workers=1,
                             heartbeat_s=0.05, reconnect_deadline_s=0.3,
                             backoff_base_s=0.01, backoff_cap_s=0.05)
        try:
            cli.push(_one())
            cli._drain()
            proxy.refuse_new()          # the partition persists...
            proxy.sever_all()           # ...and cuts every live channel
            cli.push(_one())            # sender hits the wall
            _wait_for(lambda: cli.dead is not None, timeout_s=10.0,
                      what="sender thread to surface permanent failure")
            with pytest.raises(RuntimeError, match="never applied"):
                cli.push(_one())
            with pytest.raises(RuntimeError):
                cli.gate(3)
        finally:
            cli.close()
    finally:
        proxy.close()
        svc.close()


def test_drain_timeout_raises_never_swallows():
    """_drain expiry must RAISE: a quiet return would let mark_done()/
    close() declare the run complete while the final flush is still
    un-acked — silent update loss. (The sender here is mid-reconnect with
    a LONG deadline, so self.dead stays None and only the drain's own
    timeout can fire.)"""
    params = _zeros_params()
    svc = ParamService(params, n_workers=1, liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    try:
        cli = AsyncSSPClient(0, proxy.addr, staleness=0, n_workers=1,
                             heartbeat_s=0.05, reconnect_deadline_s=30.0,
                             backoff_base_s=0.01, backoff_cap_s=0.05)
        try:
            cli.push(_one())
            cli._drain()
            proxy.refuse_new()
            proxy.sever_all()
            cli.push(_one())            # un-ackable while refused
            with pytest.raises(RuntimeError, match="un-acked"):
                cli._drain(timeout_s=0.5)
            proxy.refuse_new(False)     # lift: the replay lands after all
            cli._drain(timeout_s=10.0)
            np.testing.assert_allclose(svc.anchor["fc"]["w"], 2.0)
        finally:
            cli.close()
    finally:
        proxy.close()
        svc.close()


def test_refused_connections_do_not_consume_rule_budget():
    """Determinism: reconnect attempts landing inside a refuse_new window
    must burn neither a rule's max_conns budget nor its conn index — the
    conn=0 rule fires on the first FORWARDED connection after the window
    lifts, replay after replay."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=1, liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    rule = proxy.add_rule(FaultRule(action="drop", conn=0, max_conns=1))
    proxy.refuse_new()
    try:
        for _ in range(3):              # retries inside the refusal window
            s = socket.create_connection(proxy.addr)
            assert s.recv(1) == b""     # refused: accept-then-close
            s.close()
        assert rule.hits == 0           # budget untouched
        proxy.refuse_new(False)
        cli = AsyncSSPClient(0, proxy.addr, staleness=0, n_workers=1,
                             **FAST)    # first dial eats the drop rule
        try:
            assert rule.hits == 1
            cli.push(_one())
            cli._drain()
            np.testing.assert_allclose(svc.anchor["fc"]["w"], 1.0)
        finally:
            cli.close()
    finally:
        proxy.close()
        svc.close()


def test_fault_config_defaults_resolve_into_service_and_client():
    """`config.set_fault_config` (the programmatic knob surface the
    ARCHITECTURE doc advertises) must be what None-valued constructor
    knobs resolve against, and must reject unknown knob names."""
    from poseidon_tpu import config

    defaults = config.FaultConfig()
    config.set_fault_config(liveness_timeout_s=0.25, heartbeat_s=0.05)
    try:
        svc = ParamService(_zeros_params(), n_workers=1)
        try:
            assert svc.liveness_timeout_s == 0.25
            cli = AsyncSSPClient(0, ("127.0.0.1", svc.port), staleness=0,
                                 n_workers=1)
            try:
                assert cli.heartbeat_s == 0.05
                assert cli.reconnect_deadline_s == \
                    defaults.reconnect_deadline_s
            finally:
                cli.close()
        finally:
            svc.close()
        with pytest.raises(AttributeError):
            config.set_fault_config(no_such_knob=1.0)
    finally:
        config.set_fault_config(
            heartbeat_s=defaults.heartbeat_s,
            liveness_timeout_s=defaults.liveness_timeout_s)


def test_socket_tier_importable_without_jax():
    """A plain-socket worker process (the chaos-drive children, any
    ParamService-only host) must be able to import the tier and its
    runtime helpers without paying the jax import — runtime/__init__
    resolves its heavy re-exports lazily."""
    import subprocess
    import sys
    code = (
        "import sys\n"
        "import poseidon_tpu.parallel.async_ssp\n"
        "import poseidon_tpu.runtime.retry\n"
        "import poseidon_tpu.runtime.faults\n"
        "import poseidon_tpu.runtime.metrics\n"
        "assert 'jax' not in sys.modules, 'jax leaked into socket tier'\n"
        "from poseidon_tpu.runtime import latest_snapshot  # lazy re-export\n"
        "print('ok')\n"
    )
    p = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True)
    assert p.returncode == 0, p.stdout + p.stderr
    assert p.stdout.strip() == "ok"


def test_async_tier_restart_resumes_push_stream(monkeypatch):
    """The PRODUCT restart path (`train --async_ssp` relaunched after
    preemption): a fresh AsyncSSPTier must resume this worker's push-seq
    stream past the service's applied high-water mark — a client naively
    restarting at seq 0 would have every post-restart flush swallowed by
    the exactly-once dedup, training healthy-looking but contributing
    nothing."""
    import types

    from poseidon_tpu.runtime.async_tier import AsyncSSPTier

    params = _zeros_params()
    svc = ParamService(params, n_workers=2, liveness_timeout_s=0.0)
    monkeypatch.setenv("POSEIDON_PROC_ID", "1")
    monkeypatch.setenv("POSEIDON_NUM_PROCS", "2")
    monkeypatch.delenv("POSEIDON_COORDINATOR", raising=False)

    def fake_engine(p):
        eng = types.SimpleNamespace()
        eng.params = p
        eng.train_step = types.SimpleNamespace(replicated=None)
        return eng

    def bump(tree):
        return {l: {p: np.asarray(v) + 1.0 for p, v in ps.items()}
                for l, ps in tree.items()}

    try:
        tier = AsyncSSPTier(params, staleness=10, service_port=svc.port)
        try:
            assert tier.client.clock == -1          # nothing applied yet
            eng = fake_engine(bump(tier.resume_cache))
            tier.after_iters(eng, 1)                # flush clock 0 (seq 0)
            tier.client._drain()
            assert svc.applied_seq[1] == 0
        finally:
            # preemption: sockets torn down, no bye, no done
            tier.client._stop.set()
            tier.client._sender.join(timeout=5.0)
            tier.client._push_sock.close()
            tier.client._pull_sock.close()

        # the relaunched process builds a fresh tier against the same
        # service: it must rejoin at the applied clock, not at -1
        tier2 = AsyncSSPTier(params, staleness=10, service_port=svc.port)
        try:
            assert tier2.client.clock == 0
            assert tier2.client._acked_clock == 0
            np.testing.assert_allclose(tier2.resume_cache["fc"]["w"], 1.0)
            eng2 = fake_engine(bump(tier2.resume_cache))
            tier2.after_iters(eng2, 1)              # flush clock 1 (seq 1)
            tier2.client._drain()
            assert svc.applied_seq[1] == 1          # NOT deduped
            np.testing.assert_allclose(svc.anchor["fc"]["w"], 2.0)
        finally:
            tier2.client.close()
    finally:
        svc.close()


# --------------------------------------------------------------------------- #
# the end-to-end chaos scenario (acceptance criteria)
# --------------------------------------------------------------------------- #

def test_chaos_kill_one_of_three_mid_run_then_rejoin():
    """One of three workers is hard-dropped mid-run (sever + persistent
    refusal — the proxy-level SIGKILL): survivors' gates unblock via
    eviction and they complete all clocks; the victim's training loop
    gets the failure as an exception; a restarted process rejoins from
    the anchor and contributes its remaining clocks. Exactly-once apply
    makes the final anchor deterministic: every (worker, clock) pair
    lands exactly once — 3 workers x 12 clocks = 36 increments."""
    n, n_clocks = 3, 12
    params = _zeros_params()
    svc = ParamService(params, n_workers=n, liveness_timeout_s=0.6)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    opts = dict(heartbeat_s=0.1, reconnect_deadline_s=0.3,
                backoff_base_s=0.01, backoff_cap_s=0.05)
    results, errs = {}, {}

    def go(w, **kw):
        try:
            results[w] = run_async_ssp_worker(
                w, n, params, _counting_step(w), n_clocks, staleness=2,
                client_opts=opts, **kw)
        except Exception as e:  # noqa: BLE001 — the simulated process death
            errs[w] = e

    threads = {
        0: threading.Thread(target=go, args=(0,),
                            kwargs={"service": svc}),
        1: threading.Thread(target=go, args=(1,),
                            kwargs={"service": svc}),
        # the doomed worker routes through the proxy, slightly slow so the
        # cut lands mid-run
        2: threading.Thread(target=go, args=(2,),
                            kwargs={"service_addr": proxy.addr,
                                    "slow_s": 0.03}),
    }
    try:
        for t in threads.values():
            t.start()
        _wait_for(lambda: svc.clocks[2] >= 2, timeout_s=30.0,
                  what="worker 2 to apply a few clocks")
        proxy.refuse_new()
        proxy.sever_all()
        for t in threads.values():
            t.join(timeout=60.0)
        assert not any(t.is_alive() for t in threads.values())

        # survivors completed every clock — their gates excluded the
        # evicted worker instead of wedging on its frozen clock
        assert 0 in results and 1 in results, errs
        assert results[0]["final_clock"] == n_clocks - 1
        assert results[1]["final_clock"] == n_clocks - 1
        # the victim's loop got the failure as an exception
        assert isinstance(errs[2], (RuntimeError, OSError))
        assert 2 in svc.failed_workers
        applied = svc.clocks[2]
        assert 0 <= applied < n_clocks - 1

        # "restart the process": lift the partition, rejoin, finish
        proxy.refuse_new(False)
        res2 = run_async_ssp_worker(
            2, n, params, _counting_step(2), n_clocks, staleness=2,
            service_addr=proxy.addr, rejoin=True, client_opts=opts)
        assert res2["start_clock"] == applied + 1
        assert res2["final_clock"] == n_clocks - 1
        assert 2 not in svc.failed_workers
        assert svc.rejoins >= 1
        np.testing.assert_allclose(svc.anchor["fc"]["w"],
                                   np.full((2, 2), float(n * n_clocks)))
    finally:
        proxy.close()
        svc.close()


# --------------------------------------------------------------------------- #
# round-6 advisor findings: flush cadence + SSP gate timeouts
# --------------------------------------------------------------------------- #

def _tier_engine(params):
    import types

    eng = types.SimpleNamespace()
    eng.params = params
    eng.train_step = types.SimpleNamespace(replicated=None)
    return eng


def _free_port():
    import socket as _socket

    s = _socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_after_iters_loop_flush_cadence(monkeypatch):
    from poseidon_tpu.runtime.async_tier import AsyncSSPTier

    monkeypatch.setenv("POSEIDON_PROC_ID", "0")
    monkeypatch.setenv("POSEIDON_NUM_PROCS", "1")
    monkeypatch.delenv("POSEIDON_COORDINATOR", raising=False)
    params = _zeros_params()
    tier = AsyncSSPTier(params, staleness=10, sync_every=2,
                        service_port=_free_port())
    try:
        eng = _tier_engine({l: {p: np.asarray(v) + 1.0
                                for p, v in ps.items()}
                            for l, ps in tier.resume_cache.items()})
        # 5 iterations at sync_every=2 -> exactly 2 clocks, carry 1
        tier.after_iters(eng, 5)
        tier.client._drain()
        assert tier.client.clock == 1
        assert tier._iters_since == 1
        # the anchor saw the whole delta ONCE (second flush was empty)
        np.testing.assert_allclose(tier.service.anchor["fc"]["w"], 1.0)
        # one more iteration completes the next window -> clock 2
        tier.after_iters(eng, 1)
        tier.client._drain()
        assert tier.client.clock == 2
        assert tier._iters_since == 0
        # sub-window dispatches accumulate without flushing
        tier.after_iters(eng, 1)
        assert tier.client.clock == 2
        assert tier._iters_since == 1
        tier.finish(eng)
    finally:
        if tier.service is not None:
            tier.service.close()


def test_first_clock_gate_survives_slow_compiling_peer(monkeypatch):
    """Satellite (runtime/async_tier.py:92): a peer still JIT-compiling
    its step at clock 0 (multi-minute in production) must not
    TimeoutError-kill a healthy run — the FIRST gate is generously
    scaled; later gates use the configured backstop."""
    import threading
    import time as _time

    from poseidon_tpu.parallel.async_ssp import AsyncSSPClient
    from poseidon_tpu.runtime.async_tier import AsyncSSPTier

    monkeypatch.setenv("POSEIDON_PROC_ID", "0")
    monkeypatch.setenv("POSEIDON_NUM_PROCS", "2")
    monkeypatch.delenv("POSEIDON_COORDINATOR", raising=False)
    params = _zeros_params()
    # gate_timeout far below the peer's "compile time"; first-gate scaled
    tier = AsyncSSPTier(params, staleness=0, sync_every=1,
                        service_port=_free_port(),
                        gate_timeout_s=0.4, first_gate_timeout_s=30.0)
    try:
        peer_err = []

        def slow_peer():
            try:
                cli = AsyncSSPClient(1, ("127.0.0.1", tier.client._addr[1]),
                                     staleness=0, n_workers=2)
                _time.sleep(1.5)  # "initial JIT compile"
                cli.push({l: {p: np.zeros_like(v) for p, v in ps.items()}
                          for l, ps in params.items()})
                cli._drain()
                _time.sleep(3.0)  # never reaches clock 1 in this test
                cli.close()
            except Exception as e:  # noqa: BLE001
                peer_err.append(e)

        t = threading.Thread(target=slow_peer, daemon=True)
        t.start()
        eng = _tier_engine(dict(tier.resume_cache))
        t0 = _time.time()
        tier.after_iters(eng, 1)  # gate(1) needs peer clock >= 0
        waited = _time.time() - t0
        assert waited >= 1.0, "gate should have blocked on the slow peer"
        assert tier._gated_once
        # the SECOND gate runs at the configured 0.4 s backstop: with the
        # peer never reaching clock 1, it must fail FAST (not 120 s)
        t0 = _time.time()
        with pytest.raises(TimeoutError):
            tier.after_iters(eng, 1)
        assert _time.time() - t0 < 10.0
        t.join(timeout=10)
        assert not peer_err, peer_err
    finally:
        tier.client._stop.set()
        if tier.service is not None:
            tier.service.close()


def test_first_gate_timeout_default_scales_generously(monkeypatch):
    from poseidon_tpu.runtime.async_tier import AsyncSSPTier

    monkeypatch.setenv("POSEIDON_PROC_ID", "0")
    monkeypatch.setenv("POSEIDON_NUM_PROCS", "1")
    monkeypatch.delenv("POSEIDON_COORDINATOR", raising=False)
    params = _zeros_params()
    tier = AsyncSSPTier(params, staleness=0, gate_timeout_s=120.0,
                        service_port=_free_port())
    try:
        assert tier.first_gate_timeout_s >= 1800.0
    finally:
        tier.client._stop.set()
        tier.service.close()
    tier2 = AsyncSSPTier(params, staleness=0, gate_timeout_s=600.0,
                         service_port=_free_port())
    try:
        assert tier2.first_gate_timeout_s >= 6000.0
        assert tier2.gate_timeout_s == 600.0
    finally:
        tier2.client._stop.set()
        tier2.service.close()


# --------------------------------------------------------------------------- #
# bandwidth shaping: the throttle rule + delay billing granularity
# --------------------------------------------------------------------------- #

def _echo_server():
    """Loopback echo upstream for pure data-plane shaping tests."""
    srv = socket.create_server(("127.0.0.1", 0))

    def accept_loop():
        while True:
            try:
                c, _ = srv.accept()
            except OSError:
                return

            def serve(c=c):
                try:
                    while True:
                        d = c.recv(65536)
                        if not d:
                            return
                        c.sendall(d)
                except OSError:
                    pass
            threading.Thread(target=serve, daemon=True).start()

    threading.Thread(target=accept_loop, daemon=True).start()
    return srv


def _roundtrip(addr, payload: bytes) -> float:
    """Send payload through the proxy to the echo server and read it all
    back; returns elapsed seconds."""
    c = socket.create_connection(addr)
    try:
        t0 = time.monotonic()
        c.sendall(payload)
        got = 0
        while got < len(payload):
            chunk = c.recv(65536)
            if not chunk:
                raise AssertionError(f"connection cut at {got} bytes")
            got += len(chunk)
        return time.monotonic() - t0
    finally:
        c.close()


def test_throttle_rule_shapes_bandwidth_deterministically():
    """The token-bucket throttle: 250 kB through a 200 kB/s link with a
    50 kB burst must take >= (250-50)/200 = 1.0 s — and the same run
    again lands in the same envelope (deterministic shaping, not jitter).
    An unthrottled control through the same proxy machinery stays fast."""
    srv = _echo_server()
    try:
        control = FaultProxy(srv.getsockname())
        try:
            fast = _roundtrip(control.addr, b"x" * 250_000)
        finally:
            control.close()
        proxy = FaultProxy(srv.getsockname())
        proxy.add_rule(FaultRule(action="throttle", rate_bps=200_000,
                                 burst_bytes=50_000))
        try:
            walls = [_roundtrip(proxy.addr, b"x" * 250_000)
                     for _ in range(2)]
        finally:
            proxy.close()
        assert fast < min(walls), (fast, walls)
        for w in walls:
            # c2s pays (250-50)/200 >= 1.0 s; the echoed s2c direction has
            # its own bucket and overlaps, so the floor is one direction
            assert w >= 0.9, walls
    finally:
        srv.close()


def test_throttle_rule_rejects_zero_rate():
    with pytest.raises(ValueError, match="rate_bps"):
        FaultRule(action="throttle")


def test_delay_billing_per_frame_vs_per_chunk():
    """The delay-billing fix: one 1 MB wire frame crosses ~16 recv chunks,
    so the legacy per-chunk mode bills delay_s ~16x while per-frame bills
    it once — one rule now models the SAME latency for small and large
    frames. (Both directions carry the rule; the echo pays it twice.)"""
    srv = _echo_server()
    frame = struct.pack("!Q", 1_000_000) + b"y" * 1_000_000
    try:
        per_frame = FaultProxy(srv.getsockname())
        per_frame.add_rule(FaultRule(action="delay", delay_s=0.2,
                                     delay_per="frame"))
        try:
            w_frame = _roundtrip(per_frame.addr, frame)
        finally:
            per_frame.close()
        per_chunk = FaultProxy(srv.getsockname())
        per_chunk.add_rule(FaultRule(action="delay", delay_s=0.2))
        try:
            w_chunk = _roundtrip(per_chunk.addr, frame)
        finally:
            per_chunk.close()
    finally:
        srv.close()
    # per-frame: ~2 x 0.2 s (one per direction); per-chunk: >= 16 x 0.2 s
    # on the c2s direction alone. Upper bounds stay loose (a loaded CI
    # runner adds scheduling jitter); the per-chunk LOWER bound is the
    # load-immune half of the discrimination
    assert w_frame < 2.4, w_frame
    assert w_chunk > 3.0, w_chunk
    assert w_chunk > 1.25 * w_frame, (w_chunk, w_frame)


def test_delay_billing_once_per_connection():
    """delay_per='once': connection-setup latency — two frames through
    one connection pay delay_s once per direction, not per frame."""
    srv = _echo_server()
    try:
        proxy = FaultProxy(srv.getsockname())
        proxy.add_rule(FaultRule(action="delay", delay_s=0.3,
                                 delay_per="once"))
        try:
            frame = struct.pack("!Q", 100) + b"z" * 100
            c = socket.create_connection(proxy.addr)
            try:
                t0 = time.monotonic()
                for _ in range(3):
                    c.sendall(frame)
                    got = 0
                    while got < len(frame):
                        got += len(c.recv(65536))
                wall = time.monotonic() - t0
            finally:
                c.close()
        finally:
            proxy.close()
    finally:
        srv.close()
    # one 0.3 s bill per direction = ~0.6 s total, NOT 3 x 2 x 0.3 = 1.8
    # (bound loose enough for CI scheduling jitter, tight enough to catch
    # per-frame billing)
    assert wall < 1.5, wall


def test_delay_per_frame_models_small_and_large_frames_alike():
    """The motivating bug: under per-chunk billing a 100-byte frame and a
    1 MB frame saw wildly different injected latencies from ONE rule.
    Per-frame billing makes them equal (within scheduling noise)."""
    srv = _echo_server()
    try:
        proxy = FaultProxy(srv.getsockname())
        proxy.add_rule(FaultRule(action="delay", delay_s=0.25,
                                 delay_per="frame"))
        try:
            small = _roundtrip(proxy.addr, struct.pack("!Q", 100)
                               + b"a" * 100)
            big = _roundtrip(proxy.addr, struct.pack("!Q", 900_000)
                             + b"b" * 900_000)
        finally:
            proxy.close()
    finally:
        srv.close()
    # per-chunk billing would put big ~15 x 0.25 s ahead of small; per-
    # frame keeps them within scheduling noise (loose CI-safe bound)
    assert abs(big - small) < 1.2, (small, big)


# --------------------------------------------------------------------------- #
# group severing: kill a whole slice in one atomic event (ISSUE 16)
# --------------------------------------------------------------------------- #

def test_sever_group_cuts_only_the_targeted_workers():
    """sever_group must cut EVERY connection of the targeted worker-id
    set (both the push and pull channels) and NONE of the others — the
    deterministic 'preempt one slice' event the fabric chaos suite is
    built on."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=3, liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    clients = {}
    try:
        for w in range(3):
            clients[w] = AsyncSSPClient(w, proxy.addr, staleness=2,
                                        n_workers=3, **FAST)
            clients[w].push(_one())
        # every hello has crossed the proxy: all 6 pairs carry a tag
        _wait_for(lambda: sum(1 for p in proxy._pairs
                              if p.worker is not None) >= 6,
                  what="worker-tagged pairs")
        cut = proxy.sever_group({0, 1})
        assert cut == 4, cut           # 2 workers x (push + pull)
        # the survivor's channels still work end to end: a fresh push
        # on worker 2 is acked without any reconnect
        before = clients[2].reconnects
        clients[2].push(_one())
        _wait_for(lambda: clients[2]._acked_clock == clients[2].clock,
                  what="survivor push ack")
        assert clients[2].reconnects == before
        # the severed workers' clients REDIAL (new proxied pairs) and
        # replay their un-acked stream exactly once
        for w in (0, 1):
            clients[w].push(_one())
            _wait_for(lambda w=w: clients[w]._acked_clock
                      == clients[w].clock, what=f"worker {w} replay ack")
        assert dict(svc.clocks) == {0: 1, 1: 1, 2: 1}
    finally:
        for c in clients.values():
            c.close()
        proxy.close()
        svc.close()


def test_sever_group_is_atomic_and_ignores_unknown_ids():
    """The victim set is chosen under one lock acquisition: ids with no
    live tagged pairs cut nothing, an empty set cuts nothing, and the
    pair list shrinks by exactly the cut count (no survivor is ever
    collateral damage)."""
    params = _zeros_params()
    svc = ParamService(params, n_workers=2, liveness_timeout_s=0.0)
    proxy = FaultProxy(("127.0.0.1", svc.port))
    try:
        cli = AsyncSSPClient(0, proxy.addr, staleness=0, n_workers=2,
                             **FAST)
        try:
            cli.push(_one())
            _wait_for(lambda: sum(1 for p in proxy._pairs
                                  if p.worker == 0) >= 2,
                      what="tagged pairs for worker 0")
            assert proxy.sever_group(set()) == 0
            assert proxy.sever_group({7, 8, 9}) == 0
            with proxy._lock:
                n_before = len(proxy._pairs)
            assert proxy.sever_group({0}) == 2
            with proxy._lock:
                assert len(proxy._pairs) == n_before - 2
        finally:
            cli.close()
    finally:
        proxy.close()
        svc.close()


def test_sever_group_untagged_connections_never_match():
    """A connection whose first frame is not a worker hello stays
    untagged and must survive every sever_group call (None is never a
    member of the id set) — severing by slice only ever kills identified
    members."""
    srv = _echo_server()
    try:
        proxy = FaultProxy(srv.getsockname())
        try:
            c = socket.create_connection(proxy.addr)
            try:
                # a raw frame whose payload is not a pickled hello dict
                c.sendall(struct.pack("!Q", 5) + b"xxxxx")
                _wait_for(lambda: len(proxy._pairs) == 1,
                          what="pair registered")
                _wait_for(lambda: proxy._pairs[0].sniffed,
                          what="sniff to give up")
                assert proxy._pairs[0].worker is None
                assert proxy.sever_group({0, 1, 2}) == 0
                # the link still works after the no-op sever
                got = c.recv(65536)
                assert got  # echo came back
            finally:
                c.close()
        finally:
            proxy.close()
    finally:
        srv.close()
