"""Static comm accounting (runtime/comm_stats.py): the stats.hpp analog."""

import numpy as np
import pytest


from poseidon_tpu.core.net import Net
from poseidon_tpu.models import zoo
from poseidon_tpu.parallel import CommConfig, SFB, make_mesh
from poseidon_tpu.runtime.comm_stats import (CommCostModel, comm_summary,
                                             layer_comm_table)

N_DEV = 8


@pytest.fixture(scope="module")
def lenet():
    return Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
               source_shapes=zoo.lenet_shapes(2))


def _dtype_bytes():
    from poseidon_tpu.config import policy
    return np.dtype(policy().compute_dtype).itemsize


def test_dense_allreduce_bytes(lenet):
    mesh = make_mesh()
    table = layer_comm_table(lenet, CommConfig(), mesh)
    b = _dtype_bytes()
    # conv1: 20*1*5*5 + 20 params, ring all-reduce 2*(n-1)/n
    want = 2 * (N_DEV - 1) / N_DEV * (20 * 25 + 20) * b
    assert table["conv1"]["ici_bytes_per_step"] == int(want)
    assert table["conv1"]["dcn_bytes_per_step"] == 0
    assert table["conv1"]["strategy"] == "dense"
    # dense == its own alternative: savings 1x
    assert table["conv1"]["savings_vs_dense"] == 1.0


def test_sfb_beats_dense_for_big_fc(lenet):
    mesh = make_mesh()
    cc = CommConfig(layer_strategies={"ip1": SFB})
    table = layer_comm_table(lenet, cc, mesh)
    row = table["ip1"]  # 500x800 weight, batch 2/dev
    assert row["strategy"] == "sfb"
    # factors: 16*(500+800) entries vs 400500-entry dense matrix
    assert row["savings_vs_dense"] > 5
    assert row["ici_bytes_per_step"] < row["dense_alternative_bytes"]


def test_topk_logical_bytes(lenet):
    mesh = make_mesh()
    cc = CommConfig(default_strategy="topk", topk_fraction=0.01)
    table = layer_comm_table(lenet, cc, mesh)
    b = _dtype_bytes()
    row = table["ip1"]
    k = int((500 * 800 + 500) * 0.01)
    want = 2 * (N_DEV - 1) / N_DEV * k * (4 + b)
    assert row["ici_bytes_per_step"] == pytest.approx(want, rel=0.01)
    assert row["savings_vs_dense"] > 10


def test_two_tier_split(lenet):
    mesh = make_mesh(axes=("dcn", "data"), shape=(2, 4))
    cc = CommConfig(dcn_axis="dcn", default_strategy="topk",
                    topk_fraction=0.01)
    table = layer_comm_table(lenet, cc, mesh)
    row = table["ip1"]
    b = _dtype_bytes()
    # intra-slice: dense all-reduce over 4 devices
    dense_ici = 2 * 3 / 4 * (500 * 800 + 500) * b
    assert row["ici_bytes_per_step"] == int(dense_ici)
    # inter-slice: compressed exchange over 2 slices
    assert 0 < row["dcn_bytes_per_step"] < row["ici_bytes_per_step"]
    # the dcn tier being slow is the whole point: est time is dcn-dominated
    cost = CommCostModel()
    dcn_ms = row["dcn_bytes_per_step"] / (cost.dcn_gbps * 1e9) * 1e3
    assert row["est_comm_ms"] == pytest.approx(
        dcn_ms + dense_ici / (cost.ici_gbps * 1e9) * 1e3, rel=0.05)


def test_summary_and_split():
    net = Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
              source_shapes=zoo.lenet_shapes(2))
    table = layer_comm_table(net, CommConfig(), make_mesh())
    s = comm_summary(table, measured_step_ms=10.0)
    assert s["total_bytes_per_step"] == sum(
        r["ici_bytes_per_step"] for r in table.values())
    assert 0.0 <= s["est_comm_fraction_if_unoverlapped"] <= 1.0
    assert s["measured_step_ms"] == 10.0


def test_stats_yaml_gains_comm_section(tmp_path):
    from tests.test_runtime import _memory_data, _write_mnistish_prototxt
    from poseidon_tpu.proto.messages import load_solver
    from poseidon_tpu.runtime.engine import Engine

    solver_path = _write_mnistish_prototxt(tmp_path, max_iter=4)
    eng = Engine(load_solver(solver_path), memory_data=_memory_data(),
                 output_dir=str(tmp_path))
    try:
        eng.train()
    finally:
        eng.close()
    text = (tmp_path / "stats.yaml").read_text()
    assert "comm:" in text
    assert "per_layer:" in text
    assert "est_comm_fraction_if_unoverlapped:" in text
    assert "conv1:" in text


def test_cli_time_comm_table(tmp_path, capsys):
    model = tmp_path / "deploy.prototxt"
    model.write_text("""
name: "tiny"
input: "data"
input_dim: 4 input_dim: 3 input_dim: 8 input_dim: 8
layers { name: "conv" type: CONVOLUTION bottom: "data" top: "conv"
  convolution_param { num_output: 4 kernel_size: 3
    weight_filler { type: "xavier" } } }
layers { name: "fc" type: INNER_PRODUCT bottom: "conv" top: "fc"
  inner_product_param { num_output: 2 weight_filler { type: "xavier" } } }
layers { name: "silence" type: SILENCE bottom: "fc" }
""")
    from poseidon_tpu.runtime.cli import main
    assert main(["time", "--model", str(model), "--iterations", "2",
                 "--per_layer", "--comm_devices", "8"]) == 0
    out = capsys.readouterr().out
    assert "Comm bytes/step/device over 8 devices" in out
    assert "vs dense" in out
    assert "total:" in out
