"""Multi-process distributed training over jax.distributed on localhost.

The reference tests its distributed story with multi-process binaries on
127.0.0.1 (ps/tests/petuum_ps/comm_handler/, SURVEY §4.3). Same idea: spawn 2
real processes x 4 virtual CPU devices each through scripts/launch.py --local,
train LeNet on the shared synthetic MNIST LMDB, and check both processes agree
on the final parameters (replicated state implies identical snapshots).
"""

import os
import socket
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_local_train(tmp_path, prefix: str, max_iter: int, extra_args=()):
    """Drive the REAL launcher (scripts/launch.py --local path): 2 processes
    x 4 virtual devices training lenet; returns (logs, per-process snapshot
    npz handles at max_iter)."""
    solver = tmp_path / "solver.prototxt"
    solver.write_text(f"""
net: "{REPO}/examples/mnist/lenet_train_test.prototxt"
base_lr: 0.01
lr_policy: "fixed"
momentum: 0.9
display: 5
max_iter: {max_iter}
test_interval: 0
snapshot_after_train: true
snapshot_prefix: "{prefix}"
random_seed: 5
""")
    outs = [tmp_path / "p0", tmp_path / "p1"]
    for o in outs:
        o.mkdir()
    scripts = os.path.join(REPO, "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import launch
    rc, raw_logs = launch.launch_local(
        2, 4, _free_port(),
        ["train", "--solver", str(solver), *extra_args,
         "--output_dir", str(tmp_path / "p{proc_id}")],
        capture=True)
    logs = [b.decode() for b in raw_logs]
    assert rc == 0, f"launch failed:\n{logs[0][-2000:]}\n{logs[1][-2000:]}"
    snaps = [np.load(str(o / f"{prefix}_iter_{max_iter}.solverstate.npz"))
             for o in outs]
    # replicated state: every process writes identical snapshot bytes
    assert set(snaps[0].files) == set(snaps[1].files)
    for k in snaps[0].files:
        np.testing.assert_array_equal(snaps[0][k], snaps[1][k], err_msg=k)
    return logs, snaps


@pytest.mark.skipif(not os.path.isdir(
    os.path.join(REPO, "examples/mnist/mnist_train_lmdb")),
    reason="synthetic MNIST LMDB not generated")
def test_two_process_training(tmp_path):
    logs, _ = _run_local_train(tmp_path, "lenet_mp", 12)
    # training actually progressed (loss decreased in the rank-0 log)
    assert "Iteration 10" in logs[0]


@pytest.mark.skipif(not os.path.isdir(
    os.path.join(REPO, "examples/mnist/mnist_train_lmdb")),
    reason="synthetic MNIST LMDB not generated")
def test_two_process_two_tier_training(tmp_path):
    """--dcn_slices 2 across TWO REAL PROCESSES: the dcn axis lands on the
    inter-process boundary (each process's 4 local devices form one slice) —
    exactly the topology the managed-comm tier exists for."""
    _, snaps = _run_local_train(
        tmp_path, "lenet_tier", 10,
        ["--dcn_slices", "2", "--strategy", "topk"])
    # PER-SLICE residuals (leading dim = 2 slices, not 8 devices): pins the
    # hierarchical grouping, not just that TOPK ran
    err_keys = [k for k in snaps[0].files if k.startswith("comm_error/")]
    assert err_keys
    for k in err_keys:
        assert snaps[0][k].shape[0] == 2, (k, snaps[0][k].shape)


@pytest.mark.skipif(not os.path.isdir(
    os.path.join(REPO, "examples/mnist/mnist_test_lmdb")),
    reason="synthetic MNIST LMDB not generated")
def test_two_process_cli_test_command(tmp_path):
    """`test` under 2 processes: each host scores a disjoint shard against a
    sharded eval step (the round-1 gap: pipelines built without a Shard)."""
    model = tmp_path / "net.prototxt"
    # TEST-phase-only view of the lenet train/test net
    src = open(os.path.join(REPO,
                            "examples/mnist/lenet_train_test.prototxt")).read()
    model.write_text(src)
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import launch
    rc, raw_logs = launch.launch_local(
        2, 4, _free_port(),
        ["test", "--model", str(model), "--iterations", "4"],
        capture=True)
    logs = [b.decode() for b in raw_logs]
    assert rc == 0, f"cli test failed:\n{logs[0][-2000:]}\n{logs[1][-2000:]}"
    # rank 0 prints averaged metrics; rank 1 stays quiet
    assert "loss:" in logs[0]
    assert "accuracy:" in logs[0]
    assert "loss:" not in logs[1]


@pytest.mark.skipif(not os.path.isdir(
    os.path.join(REPO, "examples/mnist/mnist_train_lmdb")),
    reason="synthetic MNIST LMDB not generated")
def test_two_process_ssp_two_tier_wire(tmp_path):
    """The full round-3 composition across TWO REAL PROCESSES: staleness on
    the inter-process (DCN) tier, dense intra-process tier, bf16 wire,
    blocked TOPK. Each process's 4 local devices form one slice; the slices
    diverge for one step and reconcile compressed bf16 deltas over the
    process boundary — the SSPAggr deployment on a real process topology."""
    logs, snaps = _run_local_train(
        tmp_path, "lenet_sspaggr", 10,
        ["--staleness", "1", "--dcn_slices", "2", "--strategy", "topk",
         "--wire_dtype", "bf16", "--topk_block", "256"])
    assert "Iteration 10" in logs[0] or "Iteration 5" in logs[0]
    # SSP state with per-slice groups: local replicas stacked (2, ...)
    local_keys = [k for k in snaps[0].files if k.startswith("local_params/")]
    assert local_keys, sorted(snaps[0].files)[:8]
    for k in local_keys:
        assert snaps[0][k].shape[0] == 2, (k, snaps[0][k].shape)


def test_two_process_lm_tensor_parallel():
    """The LM family over the REAL distributed control plane: 2 processes
    x 4 devices run dp x tp with mesh data=1 x model=8, so the Megatron
    f/g psums themselves cross the process boundary (a data=2 x model=4
    mesh would keep every model group inside one process). Loss must fall
    and both ranks must exit clean. Launched through launch_local — the
    one owner of the multi-process env contract."""
    import re
    scripts = os.path.join(REPO, "scripts")
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import launch
    rc, raw_logs = launch.launch_local(
        2, 4, _free_port(),
        ["--mode", "tp", "--data_axis", "1", "--par_axis", "8",
         "--steps", "20", "--seq", "32", "--d_model", "32",
         "--n_heads", "8", "--display", "19", "--batch", "8"],
        capture=True,
        program=[sys.executable,
                 os.path.join(REPO, "examples/lm/train_lm.py")])
    logs = [b.decode() for b in raw_logs]
    assert rc == 0, logs[0][-2000:] + logs[1][-2000:]
    losses = [float(m) for m in re.findall(r"loss (\d+\.\d+)", logs[0])]
    assert len(losses) >= 2 and losses[-1] < losses[0], losses
