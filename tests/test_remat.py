"""The HBM budget planner's regression surface (core/remat.py).

Four properties, pinned at tier-1 cost:

1. **Knapsack semantics** — zero budget means maximal remat, a budget at
   or above the peak is the identity plan, and lower budgets choose
   SUPERSETS of higher budgets' layers (monotone in the budget; the
   greedy order is fixed so every mesh participant plans identically).
2. **Bitwise parity** — remat changes what XLA's buffer assignment keeps
   live, never the math. Checkpointed arms must equal stored-activation
   arms bit for bit: through bare train steps, through full Engine runs
   (same seed, same data), through the dp2 x fsdp2 sharded step, and per
   transformer checkpoint policy.
3. **Plan resolution** — the legacy bool folds to the enum, explicit
   config vs concrete plan disagreement refuses loudly (never silently
   arbitrated), and ``auto`` defers.
4. **Tuner integration** — the (remat, batch_size) stage persists and
   memo-hits; a default win must not ship a budget knob that would make
   later trains re-pay the measuring compile.
"""

import os

import jax
import numpy as np
import pytest

from poseidon_tpu.core import remat as remat_mod
from poseidon_tpu.core.net import Net
from poseidon_tpu.core.remat import (RematPlan, normalize_policy,
                                     plan_remat, resolve_lm_policy,
                                     wrap_checkpoint)
from poseidon_tpu.models import zoo
from poseidon_tpu.parallel import (CommConfig, build_train_step,
                                   init_train_state, make_mesh)
from poseidon_tpu.proto.messages import SolverParameter

N_DEV = 8
SP = SolverParameter(base_lr=0.01, lr_policy="fixed", momentum=0.9,
                     weight_decay=0.0005)


@pytest.fixture(scope="module", autouse=True)
def _release_compiled_steps():
    yield
    jax.clear_caches()


def _tree_equal(a, b, what=""):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for i, (x, y) in enumerate(zip(la, lb)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                      err_msg=f"{what} leaf {i}")


# --------------------------------------------------------------------------- #
# policy enum + resolution
# --------------------------------------------------------------------------- #

def test_normalize_policy_folds_legacy_bools():
    assert normalize_policy(False) == "none"
    assert normalize_policy(None) == "none"
    assert normalize_policy("") == "none"
    # True folds to jax.checkpoint's own default so the legacy bool keeps
    # its exact graph (the seed wrapped blocks in bare jax.checkpoint)
    assert normalize_policy(True) == "nothing_saveable"
    assert normalize_policy("NOTHING_SAVEABLE") == "nothing_saveable"
    with pytest.raises(ValueError, match="unknown remat policy"):
        normalize_policy("everything_saveable")


def test_resolve_lm_policy_conflict_refuses_loudly():
    # explicit config flag vs a concrete contradicting plan: error, not
    # silent arbitration
    with pytest.raises(ValueError, match="remat policy conflict"):
        resolve_lm_policy("nothing_saveable", "dots_saveable")
    # agreement passes through
    assert resolve_lm_policy("dots_saveable",
                             "dots_saveable") == "dots_saveable"
    # unset config follows the plan; auto defers; both-defer -> measured
    # default
    assert resolve_lm_policy(False, "nothing_saveable") == \
        "nothing_saveable"
    assert resolve_lm_policy("auto", "none") == "none"
    assert resolve_lm_policy("auto", None) == "dots_saveable"
    assert resolve_lm_policy(False, None) == "none"


# --------------------------------------------------------------------------- #
# the knapsack
# --------------------------------------------------------------------------- #

_TABLE = {
    # flops column is the attribution table's 3x-forward convention
    "cheap_big": {"act_bytes": 1000, "flops": 300.0},    # 0.1 flop/byte
    "mid": {"act_bytes": 500, "flops": 1500.0},          # 1 flop/byte
    "dear_small": {"act_bytes": 100, "flops": 3000.0},   # 10 flop/byte
    "scalar_head": {"act_bytes": 0, "flops": 9.0},       # never picked
}


def test_zero_budget_is_maximal_remat():
    plan = plan_remat(_TABLE, 0, 1600)
    assert set(plan.layers) == {"cheap_big", "mid", "dear_small"}
    assert plan.saved_bytes == 1600
    assert plan.active


def test_budget_at_or_above_peak_is_identity():
    plan = plan_remat(_TABLE, 1600, 1600)
    assert plan.layers == ()
    assert not plan.active
    assert plan_remat(_TABLE, 10**9, 1600).layers == ()


def test_greedy_order_is_cheapest_recompute_per_byte():
    # deficit 400: cheap_big alone (1000 bytes reclaimed) covers it
    plan = plan_remat(_TABLE, 1200, 1600)
    assert plan.layers == ("cheap_big",)
    assert plan.saved_bytes == 1000
    assert plan.recompute_flops == pytest.approx(100.0)  # 300 / 3


def test_budget_monotonicity_supersets():
    peak = 1600
    prev: set = set()
    for budget in (peak, 1200, 600, 100, 0):
        layers = set(plan_remat(_TABLE, budget, peak).layers)
        assert layers >= prev, (budget, layers, prev)
        prev = layers
    assert prev == {"cheap_big", "mid", "dear_small"}


def test_plan_doc_roundtrip():
    plan = plan_remat(_TABLE, 1200, 1600, lm_policy="dots_saveable",
                      source="measured")
    back = RematPlan.from_doc(plan.to_doc())
    assert back == plan


# --------------------------------------------------------------------------- #
# bitwise parity: bare step, Engine, dp2 x fsdp2
# --------------------------------------------------------------------------- #

def _lenet_setup(per_dev=2):
    net = Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
              source_shapes=zoo.lenet_shapes(per_dev))
    rows = per_dev * N_DEV
    rs = np.random.RandomState(0)
    batch = {"data": rs.randn(rows, 1, 28, 28).astype(np.float32),
             "label": rs.randint(0, 10, size=(rows,))}
    return net, batch


def _run_steps(net, batch, remat_plan, n_steps=3):
    comm = CommConfig(param_arena=True)
    ts = build_train_step(net, SP, make_mesh(), comm,
                          remat_plan=remat_plan)
    p = net.init(jax.random.PRNGKey(0))
    s = init_train_state(p, comm, N_DEV)
    for i in range(n_steps):
        p, s, m = ts.step(p, s, batch, jax.random.fold_in(
            jax.random.PRNGKey(7), i))
    return p, s, m


def test_lenet_step_bitwise_parity_under_max_remat():
    net, batch = _lenet_setup()
    from poseidon_tpu.runtime.attribution import layer_cost_table
    plan = plan_remat(layer_cost_table(net), 0, 0,
                      candidates=remat_mod.remat_candidates(net))
    assert plan.active
    p0, s0, m0 = _run_steps(net, batch, None)
    p1, s1, m1 = _run_steps(net, batch, plan)
    _tree_equal(p0, p1, "params")
    _tree_equal(s0, s1, "state")
    np.testing.assert_array_equal(np.asarray(m0["loss"]),
                                  np.asarray(m1["loss"]))


def test_unknown_remat_layer_refuses_loudly():
    net, batch = _lenet_setup()
    with pytest.raises(ValueError, match="unknown"):
        _run_steps(net, batch, RematPlan(layers=("not_a_layer",),
                                         source="flag"), n_steps=1)


def test_engine_bitwise_parity_with_remat_flag(tmp_path):
    """Full Engine runs (same seed, same MEMORY_DATA): the --remat flag
    arm's final params equal the stored-activation arm's bit for bit."""
    from poseidon_tpu.proto.messages import load_net_from_string
    from poseidon_tpu.runtime.engine import Engine

    net_txt = """
name: "SmallNet"
layers {
  name: "mnist" type: MEMORY_DATA top: "data" top: "label"
  memory_data_param { batch_size: 8 channels: 1 height: 12 width: 12 }
}
layers {
  name: "conv1" type: CONVOLUTION bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 3
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "relu1" type: RELU bottom: "conv1" top: "conv1" }
layers {
  name: "ip1" type: INNER_PRODUCT bottom: "conv1" top: "ip1"
  inner_product_param { num_output: 5
    weight_filler { type: "xavier" } bias_filler { type: "constant" } }
}
layers { name: "loss" type: SOFTMAX_LOSS bottom: "ip1" bottom: "label"
  top: "loss" }
"""
    rs = np.random.RandomState(0)
    md = {"data": rs.randn(64, 1, 12, 12).astype(np.float32),
          "label": rs.randint(0, 5, size=64)}
    finals = {}
    for arm, remat in (("stored", None), ("remat", "conv1,ip1")):
        sp = SolverParameter(train_net_param=load_net_from_string(net_txt),
                             base_lr=0.05, lr_policy="fixed", momentum=0.9,
                             weight_decay=0.0005, display=0, max_iter=8,
                             random_seed=3)
        out_dir = tmp_path / arm
        out_dir.mkdir()
        eng = Engine(sp, memory_data=md, output_dir=str(out_dir),
                     remat=remat)
        try:
            eng.train()
            finals[arm] = jax.device_get(eng.params)
            if remat:
                assert eng.remat_plan is not None
                assert eng.remat_plan.source == "flag"
                assert set(eng.remat_plan.layers) == {"conv1", "ip1"}
        finally:
            eng.close()
    _tree_equal(finals["stored"], finals["remat"], "engine params")


def test_spmd_dp2_fsdp2_bitwise_parity():
    from poseidon_tpu.config import MeshConfig
    from poseidon_tpu.parallel.spmd import (ShardingPlan,
                                            build_spmd_train_step,
                                            named_mesh)
    from poseidon_tpu.runtime.attribution import layer_cost_table

    cfg = MeshConfig.parse("dp2,fsdp2")
    mesh = named_mesh(cfg)
    comm = CommConfig(param_arena=True)
    net = Net(zoo.lenet(with_accuracy=False), phase="TRAIN",
              source_shapes=zoo.lenet_shapes(4))
    plan = ShardingPlan.build(net, cfg, comm)
    rplan = plan_remat(layer_cost_table(net), 0, 0,
                       candidates=remat_mod.remat_candidates(net))
    rs = np.random.RandomState(0)
    batch = {"data": rs.randn(16, 1, 28, 28).astype(np.float32),
             "label": rs.randint(0, 10, size=(16,))}
    finals = {}
    for arm, rp in (("stored", None), ("remat", rplan)):
        ts = build_spmd_train_step(net, SP, mesh, plan, comm,
                                   donate=False, remat_plan=rp)
        p = net.init(jax.random.PRNGKey(0))
        s = init_train_state(p, comm, plan.n_dp)
        for i in range(2):
            p, s, m = ts.step(p, s, batch, jax.random.fold_in(
                jax.random.PRNGKey(5), i))
        finals[arm] = jax.device_get(p)
    _tree_equal(finals["stored"], finals["remat"], "spmd params")


def test_transformer_per_policy_loss_parity():
    """GPT-small-pattern block stack (CPU-sized): every checkpoint policy
    produces the bitwise-identical LOSS (the forward replay is the same
    program). Gradients are allclose, not bitwise: the rematerialized
    backward is a structurally different graph, so XLA's fusion reorders
    reductions by ULPs — unlike the CNN per-layer checkpoint arms, whose
    backward parity stays exact (pinned above)."""
    import jax.numpy as jnp
    from poseidon_tpu.models.transformer import (TransformerConfig,
                                                 forward, init_params,
                                                 lm_loss)

    cfg = TransformerConfig(vocab_size=128, d_model=64, n_heads=4,
                            n_layers=2, d_ff=128, max_seq=32, remat=False)
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 128)
    tgts = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, 128)

    def run(policy):
        def loss(p):
            return lm_loss(forward(p, cfg, toks, remat_policy=policy),
                           tgts)
        return jax.jit(jax.value_and_grad(loss))(params)

    base_l, base_g = run("none")
    for policy in ("dots_saveable", "nothing_saveable"):
        l, g = run(policy)
        np.testing.assert_array_equal(np.asarray(base_l), np.asarray(l),
                                      err_msg=policy)
        for i, (x, y) in enumerate(zip(jax.tree_util.tree_leaves(base_g),
                                       jax.tree_util.tree_leaves(g))):
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-6,
                err_msg=f"grads[{policy}] leaf {i}")


def test_wrap_checkpoint_identity_for_none():
    fn = lambda x: x * 2  # noqa: E731
    assert wrap_checkpoint(fn, "none") is fn
    assert wrap_checkpoint(fn, "dots_saveable") is not fn


# --------------------------------------------------------------------------- #
# the measured side
# --------------------------------------------------------------------------- #

def test_measured_peak_api_and_remat_arm_stay_bounded():
    """``memory_analysis()`` reports a real peak for both arms, and the
    maximal-remat arm's peak stays within 10% of the no-remat arm's on
    toy LeNet. Direction is deliberately NOT asserted here: on the CPU
    proxy the buffer arena is conv-scratch-dominated and a toy model's
    checkpoint can land a few KiB either side — the reduction-magnitude
    claim is bench.py memory's evidence on the conv models, not a unit
    property. What this DOES catch is a remat wiring bug that doubles
    buffers or breaks the measurement API."""
    from poseidon_tpu.runtime.tuned_plan import _build_step_arm

    shapes = {"data": (2, 1, 28, 28), "label": (2,)}
    np_ = zoo.lenet(with_accuracy=False)
    base = _build_step_arm(np_, shapes, "", 4.0, 1, "", remat="",
                           measure_peak=True)
    full = _build_step_arm(np_, shapes, "", 4.0, 1, "", remat="auto",
                           measure_peak=True)
    assert base.peak_bytes > 0, "memory_analysis() returned no peak"
    assert full.peak_bytes > 0
    assert abs(full.peak_bytes - base.peak_bytes) / base.peak_bytes < 0.10


def test_plan_for_net_step_measured_source():
    net, batch = _lenet_setup()
    comm = CommConfig(param_arena=True)
    ts = build_train_step(net, SP, make_mesh(), comm)
    p = net.init(jax.random.PRNGKey(0))
    s = init_train_state(p, comm, N_DEV)
    import jax.numpy as jnp
    args = (p, s, {k: jnp.asarray(v) for k, v in batch.items()},
            jax.random.PRNGKey(7))
    tight = remat_mod.plan_for_net_step(net, ts.lowerable, args, 1)
    assert tight.source == "measured"
    assert tight.measured_peak_bytes > 0
    assert tight.active          # 1-byte budget cannot fit: must remat
    roomy = remat_mod.plan_for_net_step(net, ts.lowerable, args, 10**12)
    assert not roomy.active      # fits: identity plan


# --------------------------------------------------------------------------- #
# tuner integration: the (remat, batch) pair persists and memo-hits
# --------------------------------------------------------------------------- #

def test_tune_remat_batch_stage_persists_and_memo_hits(tmp_path):
    from poseidon_tpu.runtime.tuned_plan import run_tune

    first = run_tune("lenet", smoke=True, cache_dir=str(tmp_path),
                     knobs=["remat_batch"], windows=2, iters=2)
    assert first["source"] == "measured"
    knobs = first["doc"]["knobs"]
    trial = first["doc"]["trials"]["remat_batch"]
    assert "remat" in knobs and "batch_size" in knobs \
        and "hbm_budget_gb" in knobs
    # the cap is recorded, never silent
    assert trial["max_doublings"] >= 1
    assert "winner" in trial
    if knobs["remat"] == "":
        # a default win must not ship a budget that would make every
        # later train run re-pay the measuring compile
        assert knobs["hbm_budget_gb"] == 0.0
    second = run_tune("lenet", smoke=True, cache_dir=str(tmp_path),
                      knobs=["remat_batch"], windows=2, iters=2)
    assert second["source"] == "persisted"
    assert second["doc"]["knobs"] == knobs
