"""LLM serving suite (ISSUE 17): paged KV-cache pool + continuous
batching + tp-sharded replicas behind the fleet front door.

The contracts pinned here:

- paged decode is BITWISE equal to the dense-cache ``generate`` path
  (logits, not just argmax tokens) and the pool leaks nothing;
- the continuous scheduler returns exactly the dense path's tokens under
  concurrent submits, retires on EOS immediately, streams cumulative
  chunks, sheds/deadlines explicitly, and its static mode gang-batches;
- a tp2-sharded replica (GSPMD over the 8-device virtual CPU mesh from
  conftest) matches the one-device output token-for-token;
- kill-1-of-3 mid-generation through the real socket front door and the
  fault proxy loses ZERO accepted requests (survivor re-prefills) and
  frees every page;
- rolling reload swaps generate replicas with zero request failures.

Everything binds port 0 on loopback only; daemon threads only.
"""

import threading
import time

import numpy as np
import pytest

pytestmark = pytest.mark.serving

VOCAB = 64


def _cfg():
    from poseidon_tpu.models.transformer import TransformerConfig
    return TransformerConfig(vocab_size=VOCAB, d_model=32, n_heads=4,
                             n_layers=2, d_ff=128, max_seq=32)


def _params(cfg, seed=0):
    import jax
    from poseidon_tpu.models.transformer import init_params
    return init_params(cfg, jax.random.PRNGKey(seed))


def _prompts(b, p, seed=1):
    import jax
    import jax.numpy as jnp
    return np.asarray(jax.random.randint(jax.random.PRNGKey(seed), (b, p),
                                         0, VOCAB, dtype=jnp.int32))


def _dense(params, cfg, prompt, max_new):
    import jax.numpy as jnp
    from poseidon_tpu.models.generate import generate
    toks, logits = generate(params, cfg, jnp.asarray(prompt), max_new)
    return np.asarray(toks), np.asarray(logits)


def _executor(cfg, params, **kw):
    from poseidon_tpu.serving.continuous import GenerateExecutor
    kw.setdefault("page_size", 4)
    kw.setdefault("decode_rungs", (1, 2, 4))
    kw.setdefault("prompt_buckets", (8,))
    kw.setdefault("max_seq_len", 24)
    kw.setdefault("default_max_new", 6)
    return GenerateExecutor(cfg, params, **kw)


# --------------------------------------------------------------------------- #
# paged decode parity (the refactor's bitwise contract)
# --------------------------------------------------------------------------- #

def test_paged_decode_bitwise_equals_dense_generate():
    """Page-table indirection reconstructs the dense cache EXACTLY: the
    per-step logits (not just the argmax) are bit-identical to
    ``generate``'s, and freeing returns every page."""
    import jax
    import jax.numpy as jnp
    from poseidon_tpu.models.generate import (paged_decode_step,
                                              prefill_cached)
    from poseidon_tpu.serving.kv_pool import PagedKVPool

    cfg = _cfg()
    params = _params(cfg)
    B, P, MAX_NEW = 2, 6, 6
    prompt = _prompts(B, P)
    toks_d, logits_d = _dense(params, cfg, prompt, MAX_NEW)

    pool = PagedKVPool(cfg, num_pages=16, page_size=4,
                       max_seq_len=P + MAX_NEW)
    pf = jax.jit(prefill_cached, static_argnames=("cfg", "total"))
    step = jax.jit(lambda p, tok, caches, table, pos:
                   paged_decode_step(p, cfg, tok, caches, table, pos))

    toks_p = np.zeros((B, MAX_NEW), np.int64)
    logits_p = np.zeros_like(logits_d)
    seq_ids = list(range(B))
    for b in seq_ids:
        pool.alloc(b, P + MAX_NEW)
        lg, caches = pf(params, cfg, jnp.asarray(prompt[b:b + 1]),
                        jnp.asarray([P - 1], jnp.int32), total=8)
        pool.write_prefill(b, caches)
        logits_p[b, 0] = np.asarray(lg)[0]
    toks_p[:, 0] = np.argmax(logits_p[:, 0], axis=-1)

    table = jnp.asarray(pool.table(seq_ids))
    pos = jnp.full((B,), P, jnp.int32)
    tok = jnp.asarray(toks_p[:, 0].astype(np.int32))
    caches = pool.caches
    for i in range(1, MAX_NEW):
        lg, caches = step(params, tok, caches, table, pos)
        logits_p[:, i] = np.asarray(lg)
        toks_p[:, i] = np.argmax(logits_p[:, i], axis=-1)
        tok = jnp.asarray(toks_p[:, i].astype(np.int32))
        pos = pos + 1
    pool.caches = caches

    np.testing.assert_array_equal(toks_d, toks_p)
    assert np.array_equal(logits_d, logits_p), (
        "paged decode logits drifted from the dense cache "
        f"(max abs diff {np.abs(logits_d - logits_p).max()})")
    for b in seq_ids:
        pool.free(b)
    assert pool.all_free()


def test_pool_reserve_all_or_nothing_and_exhaustion():
    """Admission reserves the WHOLE sequence budget up front: a request
    that cannot get every page gets none, and retirement returns the
    exact pages taken (no mid-flight exhaustion, no leak)."""
    from poseidon_tpu.serving.kv_pool import PagedKVPool, PoolExhausted

    cfg = _cfg()
    pool = PagedKVPool(cfg, num_pages=5, page_size=4, max_seq_len=16)
    # 4 usable pages (page 0 is scratch): 16 tokens = all 4 pages
    pool.alloc(1, 16)
    assert not pool.can_admit(4)
    with pytest.raises(PoolExhausted):
        pool.alloc(2, 4)
    pool.free(1)
    assert pool.all_free()
    pool.alloc(3, 4)
    pool.free(3)
    assert pool.all_free()


# --------------------------------------------------------------------------- #
# continuous scheduler behavior
# --------------------------------------------------------------------------- #

def test_scheduler_matches_dense_eos_and_streaming():
    """Concurrent submits through the iteration-level scheduler produce
    exactly the dense path's tokens; EOS retires a sequence on the spot
    (n_new == 1 when the first token is EOS); streaming chunks are
    cumulative with the final chunk equal to the result."""
    cfg = _cfg()
    params = _params(cfg)
    B, P, MAX_NEW = 3, 6, 6
    prompt = _prompts(B, P)
    toks_d, _ = _dense(params, cfg, prompt, MAX_NEW)

    ex = _executor(cfg, params)
    sched = ex.make_batcher(max_queue=16)
    try:
        results = [None] * B
        errs = [None] * B

        def worker(i):
            try:
                results[i] = sched.submit(
                    {"prompt": prompt[i], "max_new": MAX_NEW}, timeout_s=30)
            except BaseException as e:  # noqa: BLE001 — asserted below
                errs[i] = e

        ts = [threading.Thread(target=worker, args=(i,), daemon=True)
              for i in range(B)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        assert errs == [None] * B
        for i in range(B):
            np.testing.assert_array_equal(results[i]["tokens"], toks_d[i])

        eos = int(toks_d[0][0])
        r = sched.submit({"prompt": prompt[0], "max_new": 6, "eos_id": eos})
        assert r["n_new"] == 1 and int(r["tokens"][0]) == eos

        chunks = []
        r = sched.submit({"prompt": prompt[1], "max_new": 4,
                          "stream": lambda t: chunks.append(list(t))})
        assert [len(c) for c in chunks] == [1, 2, 3, 4]
        assert chunks[-1] == [int(t) for t in r["tokens"]]

        assert sched.wait_idle(10.0)
        assert ex.pool.all_free(), "retirement leaked pages"
        snap = sched.snapshot()
        assert snap["admitted"] == snap["retired"] == B + 2
    finally:
        sched.close()


def test_scheduler_sheds_and_deadlines_explicitly():
    """A full queue sheds with ShedError (never a hang); a queued request
    whose deadline lapses before admission surfaces DeadlineError; both
    count in the scheduler's telemetry."""
    from poseidon_tpu.serving.batcher import DeadlineError, ShedError
    from poseidon_tpu.serving.continuous import ContinuousScheduler

    cfg = _cfg()
    params = _params(cfg)
    prompt = _prompts(1, 6)[0]
    ex = _executor(cfg, params)

    gate = threading.Event()
    real_decode = ex.decode

    def slow_decode(tok, table, pos):
        gate.wait(10.0)
        return real_decode(tok, table, pos)

    ex.decode = slow_decode
    sched = ContinuousScheduler(ex, max_queue=1)
    try:
        holder = threading.Thread(
            target=lambda: sched.submit({"prompt": prompt, "max_new": 6},
                                        timeout_s=30),
            daemon=True)
        holder.start()
        deadline = time.monotonic() + 5.0
        while sched.inflight_rows == 0:
            assert time.monotonic() < deadline, "first submit never admitted"
            time.sleep(0.005)
        # active row holds the loop inside decode; the 1-deep queue gets
        # filled by a request whose deadline is already doomed to lapse
        # before the loop can come back around to admit it
        doomed_err = []

        def doomed():
            try:
                sched.submit({"prompt": prompt, "max_new": 2},
                             deadline_s=0.01, timeout_s=30)
            except BaseException as e:  # noqa: BLE001 — asserted below
                doomed_err.append(e)

        q_filler = threading.Thread(target=doomed, daemon=True)
        q_filler.start()
        deadline = time.monotonic() + 5.0
        while sched.queue_depth == 0:
            assert time.monotonic() < deadline, "queue never filled"
            time.sleep(0.005)
        # the next submit meets a full queue: explicit shed, never a hang
        with pytest.raises(ShedError):
            sched.submit({"prompt": prompt, "max_new": 2})
        assert sched.shed_count == 1
        time.sleep(0.05)                 # the queued deadline lapses …
        gate.set()                       # … before admission resumes
        holder.join(timeout=30)
        q_filler.join(timeout=30)
        assert len(doomed_err) == 1 and isinstance(doomed_err[0],
                                                   DeadlineError)
        assert sched.deadline_expired >= 1
        assert sched.wait_idle(10.0)
        assert ex.pool.all_free()
    finally:
        gate.set()
        sched.close()


def test_static_mode_gang_admits_and_matches():
    """The A/B control arm: static mode gang-admits into an EMPTY active
    set only (no iteration-level backfill), still returns exactly the
    dense tokens, and reports its mode in the snapshot."""
    from poseidon_tpu.serving.continuous import ContinuousScheduler

    cfg = _cfg()
    params = _params(cfg)
    B, P, MAX_NEW = 4, 6, 5
    prompt = _prompts(B, P)
    toks_d, _ = _dense(params, cfg, prompt, MAX_NEW)

    ex = _executor(cfg, params)
    ex.scheduler_mode = "static"
    sched = ex.make_batcher(max_queue=16)
    try:
        assert sched.mode == "static"
        results = [None] * B

        def worker(i):
            results[i] = sched.submit(
                {"prompt": prompt[i], "max_new": MAX_NEW}, timeout_s=30)

        ts = [threading.Thread(target=worker, args=(i,), daemon=True)
              for i in range(B)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=30)
        for i in range(B):
            np.testing.assert_array_equal(results[i]["tokens"], toks_d[i])
        assert sched.snapshot()["mode"] == "static"
        assert ex.pool.all_free()
    finally:
        sched.close()


# --------------------------------------------------------------------------- #
# tp-sharded replica (PR-10 ShardingPlan composition)
# --------------------------------------------------------------------------- #

def test_tp2_sharded_replica_matches_one_device():
    """A GenerateExecutor over a tp=2 named mesh (GSPMD, head-major
    layout, sharded KV pool) produces token-for-token the one-device
    dense output — the sharding is invisible to the serving contract."""
    from poseidon_tpu.config import MeshConfig

    cfg = _cfg()
    params = _params(cfg)
    B, P, MAX_NEW = 2, 6, 6
    prompt = _prompts(B, P)
    toks_d, _ = _dense(params, cfg, prompt, MAX_NEW)

    ex = _executor(cfg, params, decode_rungs=(1, 2),
                   mesh_cfg=MeshConfig(data=1, fsdp=1, tp=2))
    sched = ex.make_batcher(max_queue=8)
    try:
        results = [None] * B

        def worker(i):
            results[i] = sched.submit(
                {"prompt": prompt[i], "max_new": MAX_NEW}, timeout_s=60)

        ts = [threading.Thread(target=worker, args=(i,), daemon=True)
              for i in range(B)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        for i in range(B):
            np.testing.assert_array_equal(results[i]["tokens"], toks_d[i])
        assert ex.pool.all_free()
        assert ex.snapshot()["mesh"]
    finally:
        sched.close()


# --------------------------------------------------------------------------- #
# the wire: generate op + streaming over the socket front door
# --------------------------------------------------------------------------- #

def test_generate_over_socket_with_streaming_and_stats():
    from poseidon_tpu.serving.client import ServingClient, run_load
    from poseidon_tpu.serving.server import InferenceServer

    cfg = _cfg()
    params = _params(cfg)
    prompt = _prompts(2, 6)
    toks_d, _ = _dense(params, cfg, prompt, 6)

    ex = _executor(cfg, params)
    srv = InferenceServer(executor=ex)
    cli = None
    try:
        cli = ServingClient(srv.addr)
        out = cli.generate(prompt[0], max_new=6)
        np.testing.assert_array_equal(out["tokens"], toks_d[0])

        chunks = []
        out = cli.generate(prompt[1], max_new=6, on_tokens=chunks.append)
        assert [len(c) for c in chunks] == [1, 2, 3, 4, 5, 6]
        np.testing.assert_array_equal(out["tokens"], toks_d[1])

        r = run_load(srv.addr,
                     lambda i: {"prompt": prompt[i % 2], "max_new": 4},
                     n_requests=12, concurrency=3, op="generate")
        assert r["ok"] == 12 and r["error"] == 0
        assert r["tokens"] == 48 and r["goodput_tps"] > 0
        st = cli.stats()
        assert st["rows_served"] > 0
    finally:
        if cli is not None:
            cli.close()
        srv.shutdown()
    assert ex.pool.all_free()


# --------------------------------------------------------------------------- #
# chaos: kill 1 of 3 mid-generation (the acceptance scenario)
# --------------------------------------------------------------------------- #

def _poisonable_executor(cfg, params):
    """A real GenerateExecutor whose decode dies once ``die`` is set —
    the replica-death lever for a scheduler of sequences (poisoning
    decode, not prefill, kills replicas MID-generation)."""
    ex = _executor(cfg, params)
    ex.die = threading.Event()
    real_decode = ex.decode

    def decode(tok, table, pos):
        if ex.die.is_set():
            raise RuntimeError("device lost")
        return real_decode(tok, table, pos)

    ex.decode = decode
    return ex


def test_kill_one_of_three_mid_generation_chaos():
    """3 generate replicas under sustained socket load; one dies
    MID-GENERATION, then a full network partition on top. Zero accepted
    requests lost (the fleet re-prefills on a survivor), the dead
    replica's pages and the survivors' pools all return to free."""
    from poseidon_tpu.runtime.faults import FaultProxy
    from poseidon_tpu.serving.client import run_load
    from poseidon_tpu.serving.fleet import DEAD, ReplicaManager
    from poseidon_tpu.serving.server import InferenceServer

    cfg = _cfg()
    params = _params(cfg)
    prompt = _prompts(4, 6)
    exs = [_poisonable_executor(cfg, params) for _ in range(3)]
    mgr = ReplicaManager(exs, max_queue=64)
    srv = InferenceServer(fleet=mgr)
    proxy = FaultProxy(srv.addr)
    try:
        box = {}

        def load():
            box["result"] = run_load(
                proxy.addr,
                lambda i: {"prompt": prompt[i % 4], "max_new": 4},
                n_requests=120, concurrency=6, retry_deadline_s=10.0,
                op="generate")

        t = threading.Thread(target=load, daemon=True)
        t.start()
        time.sleep(0.2)
        exs[0].die.set()                 # decode dies mid-generation
        time.sleep(0.2)
        proxy.sever_all()                # partition every connection
        t.join(timeout=90.0)
        assert not t.is_alive(), "load generator wedged"
        r = box["result"]
        # the invariant: only explicit sheds are lost, nothing errors
        assert r["error"] == 0 and r["deadline"] == 0, r
        assert r["ok"] + r["shed"] == 120, r
        assert r["ok"] > 0 and r["tokens"] == r["ok"] * 4
        assert mgr.state_counts()[DEAD] == 1
        assert mgr.deaths == 1 and mgr.failovers >= 1
        # survivors carried the load
        assert exs[1].rows_served + exs[2].rows_served > 0
    finally:
        proxy.close()
        srv.shutdown()
    for i, ex in enumerate(exs):
        assert ex.pool.all_free(), f"replica {i} leaked pages"


# --------------------------------------------------------------------------- #
# rolling reload over generate replicas
# --------------------------------------------------------------------------- #

def test_rolling_reload_swaps_generate_replicas():
    """rolling_reload drains and swaps generate replicas one at a time;
    afterwards every replica serves the NEW params (output flips to the
    new dense reference) and versions bumped."""
    from poseidon_tpu.serving.fleet import ReplicaManager

    cfg = _cfg()
    params_a = _params(cfg, seed=0)
    params_b = _params(cfg, seed=9)
    prompt = _prompts(1, 6)[0]
    toks_a, _ = _dense(params_a, cfg, prompt[None, :], 5)
    toks_b, _ = _dense(params_b, cfg, prompt[None, :], 5)
    assert not np.array_equal(toks_a, toks_b), "seeds collide; bad fixture"

    exs = [_executor(cfg, params_a) for _ in range(2)]
    mgr = ReplicaManager(exs, max_queue=16)
    try:
        out, _ = mgr.submit({"prompt": prompt, "max_new": 5})
        np.testing.assert_array_equal(out["tokens"], toks_a[0])
        swapped = mgr.rolling_reload(params_b)
        assert swapped == 2
        assert mgr.max_concurrent_draining <= 1
        for _ in range(4):
            out, _ = mgr.submit({"prompt": prompt, "max_new": 5})
            np.testing.assert_array_equal(out["tokens"], toks_b[0])
        assert all(ex.params_version == 1 for ex in exs)
    finally:
        mgr.shutdown()
    assert all(ex.pool.all_free() for ex in exs)
